"""Baseline files: bank known findings so CI fails only on regressions.

Format (``tools/tpulint_baseline.json``)::

    {"version": 1, "tool": "tpulint",
     "findings": {"<finding key>": <count>,
                  "<finding key>": {"count": <n>,
                                    "justification": "<why kept>"},
                  ...}}

Keys are :attr:`Finding.key` — rule|path|scope|detail, no line numbers —
so editing unrelated lines in a banked file does not churn the baseline.
A finding is *new* when its key is absent, or when the same key now
occurs more often than banked (a second sync added next to a known one
must not hide behind it).

A plain integer value is unjustified debt (a work queue entry); the
object form records *why* the finding is accepted — required for
survivors that are exact by design (e.g. a metric series whose name is
built dynamically and is therefore invisible to the static R003 pass).
"""
from __future__ import annotations

import json
from collections import Counter
from typing import Dict, List, Optional, Tuple

from .findings import Finding

VERSION = 1


def counts(findings: List[Finding]) -> Dict[str, int]:
    return dict(Counter(f.key for f in findings))


def save(path: str, findings: List[Finding],
         justifications: Optional[Dict[str, str]] = None) -> None:
    """Bank findings; keys present in ``justifications`` are written in
    the object form so a refresh does not drop the recorded reasons."""
    justifications = justifications or {}
    entries: Dict[str, object] = {}
    for key, n in sorted(counts(findings).items()):
        why = justifications.get(key)
        entries[key] = {"count": n, "justification": why} if why else n
    payload = {
        "version": VERSION,
        "tool": "tpulint",
        "findings": entries,
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def _load_payload(path: str) -> dict:
    with open(path) as f:
        payload = json.load(f)
    if payload.get("version") != VERSION:
        raise ValueError(
            f"{path}: unsupported tpulint baseline version "
            f"{payload.get('version')!r}")
    return payload


def load(path: str) -> Dict[str, int]:
    """Key -> banked count, normalizing both value forms."""
    out: Dict[str, int] = {}
    for key, val in _load_payload(path).get("findings", {}).items():
        if isinstance(val, dict):
            out[key] = int(val.get("count", 1))
        else:
            out[key] = int(val)
    return out


def load_justifications(path: str) -> Dict[str, str]:
    """Key -> recorded justification, for entries that carry one."""
    out: Dict[str, str] = {}
    for key, val in _load_payload(path).get("findings", {}).items():
        if isinstance(val, dict) and val.get("justification"):
            out[key] = str(val["justification"])
    return out


def diff(findings: List[Finding],
         banked: Dict[str, int]) -> Tuple[List[Finding], int]:
    """Return (new findings not covered by the baseline, stale count).

    Stale = banked occurrences that no longer fire; surfaced so a
    baseline refresh can shrink the debt ledger as fixes land.
    """
    remaining = dict(banked)
    new: List[Finding] = []
    for f in findings:
        if remaining.get(f.key, 0) > 0:
            remaining[f.key] -= 1
        else:
            new.append(f)
    stale = sum(v for v in remaining.values() if v > 0)
    return new, stale
