"""tpulint CLI (driven by ``tools/tpulint.py``).

Usage::

    python tools/tpulint.py [paths…] [--zoo] [--concurrency]
        [--contracts] [--format text|json]
        [--baseline tools/tpulint_baseline.json] [--write-baseline FILE]
        [--fail-on high|any|none]

Source paths get the AST pass; ``--zoo`` additionally traces a
representative set of model-zoo networks through the jaxpr pass (pure
tracing — no FLOP executes, so the whole run stays CPU-cheap);
``--concurrency`` runs the interprocedural lock-order / blocking-under-
lock / thread-lifecycle C-rules; ``--contracts`` runs the R-rules
(swallowed faults, untyped raises, and the code↔docs drift gates for
chaos sites, env vars and metric series). With ``--baseline``, only
*new* findings at or above ``--fail-on`` fail the run (exit 1);
``--write-baseline`` banks the current findings as the accepted debt
ledger (carrying forward any justification strings recorded in
``--baseline``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from . import ast_rules, baseline as baseline_mod
from .findings import Finding, HIGH, RULES, _SEV_ORDER, sort_findings

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# small-but-representative zoo slice: a squeeze/expand topology (odd
# channel counts — J001's bread and butter), a depthwise net, and a
# plain residual convnet. Tracing only; kept < 60 s on CPU.
ZOO_MODELS = (
    ("squeezenet1.0", (1, 3, 224, 224)),
    ("mobilenet0.25", (1, 3, 224, 224)),
    ("resnet18_v1", (1, 3, 224, 224)),
)


def lint_zoo(models=ZOO_MODELS, rewrite: bool = True,
             reports: Optional[list] = None) -> List[Finding]:
    """Trace the zoo slice through the jaxpr rules.

    ``rewrite=True`` (the gate default) first runs the cost-model-gated
    ``opt`` rewrite pass over each model and lints the **transformed**
    program — the "baseline as work queue" semantics: a J001 the
    rewriter retires (because the TPU cost model predicts a win)
    disappears from the ledger, while refused rewrites (memory-bound
    ops, grouped convs) keep their entries. Decisions are appended to
    ``reports`` (one ``RewriteReport`` per model) for the CLI to
    render, so every baseline removal carries its predicted-win
    justification."""
    import numpy as onp

    from ..gluon.model_zoo import vision
    from .jaxpr_rules import lint_block, lint_callable

    findings: List[Finding] = []
    for name, shape in models:
        net = vision.get_model(name)
        net.initialize()
        x = onp.zeros(shape, dtype="float32")
        scope = f"zoo:{name}"
        if rewrite and hasattr(net, "functionalize"):
            from .opt import CostModel, rewrite_block

            # gate for the TPU deployment target: these are TPU
            # anti-patterns, and the zoo gate runs on CPU CI
            fn, params0, report = rewrite_block(
                net, x, model=CostModel.for_backend(
                    "tpu", "TPU v5 lite"),
                mode_override="rewrite", scope=scope)
            if reports is not None:
                reports.append(report)
            import jax.numpy as jnp

            findings.extend(lint_callable(
                fn, params0, jnp.asarray(x), scope=scope))
        else:
            findings.extend(lint_block(net, x, scope=scope))
    return findings


def run(paths, zoo: bool = False, baseline_path: Optional[str] = None,
        write_baseline: Optional[str] = None, fail_on: str = "high",
        fmt: str = "text", root: Optional[str] = None,
        zoo_rewrite: bool = True, opt_report: bool = False,
        concurrency: bool = False, contracts: bool = False,
        out=None) -> int:
    out = out or sys.stdout
    root = root or REPO_ROOT
    t0 = time.perf_counter()
    findings = ast_rules.lint_paths(paths, root=root)
    if concurrency:
        from . import concurrency as concurrency_mod

        findings.extend(concurrency_mod.lint_paths(paths, root=root))
    if contracts:
        from . import contracts as contracts_mod

        findings.extend(contracts_mod.lint_paths(paths, root=root))
    reports: list = []
    if zoo:
        findings.extend(lint_zoo(rewrite=zoo_rewrite, reports=reports))
    findings = sort_findings(findings)
    if opt_report and reports and fmt != "json":
        for rep in reports:
            print(rep.render(), file=out)

    if write_baseline:
        just = (baseline_mod.load_justifications(baseline_path)
                if baseline_path and os.path.exists(baseline_path)
                else None)
        baseline_mod.save(write_baseline, findings, justifications=just)
        print(f"tpulint: banked {len(findings)} finding(s) to "
              f"{write_baseline}", file=out)
        return 0

    new, stale = findings, 0
    if baseline_path:
        banked = baseline_mod.load(baseline_path)
        new, stale = baseline_mod.diff(findings, banked)

    threshold = {"high": 0, "any": max(_SEV_ORDER.values()),
                 "none": -1}[fail_on]
    gating = [f for f in new
              if _SEV_ORDER.get(f.severity, max(_SEV_ORDER.values()))
              <= threshold]

    elapsed = time.perf_counter() - t0
    if fmt == "json":
        payload = {
            "tool": "tpulint",
            "elapsed_s": round(elapsed, 3),
            "total": len(findings),
            "new": [f.to_dict() for f in new],
            "stale_baseline_entries": stale,
            "failed": bool(gating),
        }
        if opt_report and reports:
            payload["opt"] = [r.to_dict() for r in reports]
        json.dump(payload, out, indent=1)
        out.write("\n")
    else:
        shown = new if baseline_path else findings
        for f in shown:
            print(f.render(), file=out)
        label = "new finding(s)" if baseline_path else "finding(s)"
        print(f"tpulint: {len(shown)} {label} "
              f"({len(findings)} total, {stale} stale baseline entr"
              f"{'y' if stale == 1 else 'ies'}) in {elapsed:.1f}s",
              file=out)
        if gating:
            print(f"tpulint: FAIL — {len(gating)} new finding(s) at "
                  f"severity >= {fail_on}", file=out)
    return 1 if gating else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint",
        description="TPU anti-pattern analyzer over jaxprs and source")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO_ROOT, "mxnet_tpu")],
                    help="files/directories to lint "
                         "(default: the mxnet_tpu package)")
    ap.add_argument("--zoo", action="store_true",
                    help="also trace representative model-zoo networks "
                         "through the jaxpr rules (post-rewrite: the "
                         "opt pass runs first; see --no-zoo-rewrite)")
    ap.add_argument("--no-zoo-rewrite", dest="zoo_rewrite",
                    action="store_false",
                    help="lint the zoo AS WRITTEN, without the cost-"
                         "model-gated opt rewrite pass (shows the full "
                         "pre-rewrite debt)")
    ap.add_argument("--concurrency", action="store_true",
                    help="run the C-rules: interprocedural lock-order "
                         "cycles (C001), blocking-under-lock (C002), "
                         "thread-lifecycle leaks (C003)")
    ap.add_argument("--contracts", action="store_true",
                    help="run the R-rules: swallowed faults (R001), "
                         "untyped raises (R002), and the code<->docs "
                         "drift gates for chaos sites, MXNET_TPU_* env "
                         "vars and metric series (R003)")
    ap.add_argument("--opt-report", action="store_true",
                    help="with --zoo: print each model's rewrite "
                         "decisions (applied + refused, with the cost-"
                         "model predicted gain that justifies each)")
    ap.add_argument("--format", dest="fmt", choices=("text", "json"),
                    default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON; only new findings gate")
    ap.add_argument("--write-baseline", default=None,
                    help="bank current findings and exit 0")
    ap.add_argument("--fail-on", choices=("high", "any", "none"),
                    default="high",
                    help="minimum severity of NEW findings that fails the "
                         "run (default: high)")
    ap.add_argument("--root", default=None,
                    help="root for repo-relative paths in finding keys "
                         "(default: the repo root)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, (sev, desc) in sorted(RULES.items()):
            print(f"{rule} [{sev:6s}] {desc}")
        return 0

    return run(args.paths, zoo=args.zoo, baseline_path=args.baseline,
               write_baseline=args.write_baseline, fail_on=args.fail_on,
               fmt=args.fmt, root=args.root,
               zoo_rewrite=args.zoo_rewrite,
               opt_report=args.opt_report,
               concurrency=args.concurrency, contracts=args.contracts)
