"""tpulint finding model + rule catalog.

A finding is one detected TPU anti-pattern: a rule id, a severity, a
location (``file:line`` for source findings, a jaxpr/model scope for IR
findings), a human message and a fix hint. Findings are hashable into a
*stable key* (no line numbers — line drift must not churn baselines) so a
checked-in baseline can separate known debt from regressions.

Severity contract (what the CI gate keys on):
- ``high``   — falls off the TPU fast path or silently breaks the jit
               cache; new ones fail the tier-1 self-lint gate.
- ``medium`` — pays real padding/conversion cost; reported, not gating.
- ``low``    — style-level dtype hygiene; informational.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

HIGH = "high"
MEDIUM = "medium"
LOW = "low"

_SEV_ORDER = {HIGH: 0, MEDIUM: 1, LOW: 2}


# rule id -> (severity, one-line description). The single source of truth:
# docs/static_analysis.md and the CLI --list-rules output render from it.
RULES: Dict[str, tuple] = {
    # jaxpr-level (J*)
    "J001": (MEDIUM, "tpu-dot-align: matmul/conv operand dim pads badly "
                     "against the (8, 128) sublane/lane tiles"),
    "J002": (HIGH, "tpu-f64-leak: float64 value inside a traced program "
                   "(TPUs have no f64 ALU; XLA software-emulates it)"),
    "J003": (MEDIUM, "tpu-convert-churn: dtype converted away and back "
                     "(convert_element_type round-trip)"),
    "J004": (MEDIUM, "tpu-scalar-reduce: full reduction to a scalar "
                     "program output — a host-sync magnet"),
    "J005": (HIGH, "tpu-donation-miss: buffer updated in place but not in "
                   "donate_argnums — double HBM footprint per step"),
    # source-level (A*)
    "A001": (HIGH, "tpu-host-sync-hot: device->host sync "
                   "(float()/.item()/.asnumpy()/np.asarray/iteration) "
                   "inside a hot path"),
    "A002": (HIGH, "tpu-cache-key-hazard: env knob read under trace but "
                   "absent from every jit cache key"),
    "A003": (LOW, "tpu-f64-source: float64 dtype literal in framework "
                  "source"),
    # concurrency (C*) — AST + the lockwatch runtime witness
    "C001": (HIGH, "tpu-lock-cycle: cycle in the interprocedural "
                   "lock-order graph — a potential deadlock"),
    "C002": (HIGH, "tpu-blocking-under-lock: blocking call (socket/"
                   "subprocess/sleep/untimed wait/compile) while a lock "
                   "is held — the PR-11 restart() outage shape"),
    "C003": (HIGH, "tpu-thread-leak: non-daemon Thread started without "
                   "a reachable join — leaks one thread per start"),
    # contract drift (R*) — AST + docs cross-check
    "R001": (MEDIUM, "tpu-swallowed-except: bare/overbroad except that "
                     "swallows without re-raising or logging in a "
                     "retry/collective path"),
    "R002": (MEDIUM, "tpu-untyped-raise: raise of an untyped builtin "
                     "operational exception in a module bound to the "
                     "TransientError/FatalError taxonomy"),
    "R003": (HIGH, "tpu-contract-drift: chaos sites / MXNET_TPU_* env "
                   "vars / telemetry series out of sync between code "
                   "and the docs contract tables"),
}


@dataclass
class Finding:
    rule: str
    message: str
    path: str = ""                 # repo-relative file, or "" for IR scopes
    line: int = 0                  # 1-based; 0 = not line-anchored
    scope: str = ""                # enclosing function / model name
    detail: str = ""               # stable discriminator (dim sizes, knob…)
    hint: str = ""
    severity: str = field(default="")

    def __post_init__(self):
        if not self.severity:
            self.severity = RULES.get(self.rule, (MEDIUM, ""))[0]

    @property
    def key(self) -> str:
        """Baseline identity: everything except the line number."""
        return "|".join((self.rule, self.path, self.scope,
                         self.detail or self.message))

    @property
    def location(self) -> str:
        if self.path and self.line:
            return f"{self.path}:{self.line}"
        return self.path or self.scope or "<ir>"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "location": self.location,
            "path": self.path,
            "line": self.line,
            "scope": self.scope,
            "detail": self.detail,
            "message": self.message,
            "hint": self.hint,
        }

    def render(self) -> str:
        loc = self.location
        txt = f"{loc}: [{self.rule}/{self.severity}] {self.message}"
        if self.scope and self.path:
            txt += f" (in {self.scope})"
        if self.hint:
            txt += f"\n    hint: {self.hint}"
        return txt


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (_SEV_ORDER.get(f.severity, 9),
                                           f.path, f.line, f.rule))


def max_severity(findings: List[Finding]) -> Optional[str]:
    if not findings:
        return None
    return min((f.severity for f in findings),
               key=lambda s: _SEV_ORDER.get(s, 9))
