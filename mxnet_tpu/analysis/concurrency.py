"""Concurrency rules (the C* half of tpulint) — AST passes over the
framework source that make lock discipline *statically visible*.

PRs 9–17 turned the single-process runtime into a cluster of threads:
Router control loops, replica reapers, heartbeat beaters, autoscaler
loops, BlockServer accept threads, decode workers. Every hardening pass
found the same bug classes by hand — ``restart()`` building a replica
while holding the pool lock (a fleet-wide routing outage), a heartbeat
thread leaked per restart, a reaper closing the wrong engine. These
passes catch those classes before runtime:

- **C001 tpu-lock-cycle** — build the interprocedural lock-order graph
  (every ``threading.Lock``/``RLock``/``Condition`` acquired via
  ``with`` or ``.acquire()``; an edge A→B when B is taken while A is
  held, including through direct intra-package calls) and flag every
  cycle as a potential deadlock.
- **C002 tpu-blocking-under-lock** — a blocking call under a held lock:
  socket ``recv``/``accept``/``connect``, ``subprocess`` waits,
  ``time.sleep``, ``Event.wait``/``Thread.join`` without a timeout, and
  jit/AOT compile entry points (the exact shape of the PR-11
  ``restart()`` outage). ``Condition.wait`` on the *held* condition is
  exempt — it releases the lock by contract.
- **C003 tpu-thread-leak** — a ``threading.Thread`` started without
  ``daemon=True`` and without a reachable ``join()`` on the stored
  handle (the per-restart heartbeat-beater leak class).

Lock identity is structural — ``module.Class.attr`` for instance locks,
``module.attr`` for module globals — so the graph is stable across line
edits (baseline keys never carry line numbers). The static graph is
validated against real executions by :mod:`.lockwatch`, the runtime
witness armed inside the fleet/io kill drills.

Suppression: the shared ``# tpulint: disable=C002`` inline comment
grammar from :mod:`.ast_rules` applies to every C rule.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .ast_rules import _suppressions, _suppressed, _unparse, iter_py_files
from .findings import Finding

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

#: attribute / function names whose call blocks the calling thread.
#: value = the human label rendered into the finding.
BLOCKING_ATTRS = {
    "sleep": "time.sleep",
    "recv": "socket recv",
    "recv_into": "socket recv",
    "accept": "socket accept",
    "connect": "socket connect",
    "create_connection": "socket connect",
    "communicate": "subprocess wait",
    "check_output": "subprocess wait",
    "check_call": "subprocess wait",
    "select": "select wait",
}
#: names that block only when called WITHOUT a timeout argument.
BLOCKING_NO_TIMEOUT_ATTRS = {
    "wait": "Event/Condition wait",
    "join": "thread join",
    "get": "queue get",
}
#: compile entry points — a cold build/warm under a lock is the PR-11
#: fleet outage shape (every router tick blocked behind the build).
#: ``lower`` only counts when called with arguments (``str.lower()``
#: takes none); ``re.compile`` is exempt by receiver.
COMPILE_ATTRS = {
    "warmup": "AOT warmup",
    "warm_from_manifest": "AOT manifest warm",
    "cached_jit": "AOT cached_jit",
    "lower": "jit lower",
    "compile": "jit compile",
}
#: bare-name calls (module-level function calls) that block.
BLOCKING_NAMES = {
    "sleep": "time.sleep",
    "create_connection": "socket connect",
    "run": None,  # only blocking as subprocess.run — resolved via module
}

_MAX_DEPTH = 6  # interprocedural propagation bound (fixpoint iterations)


# ---------------------------------------------------------------------------
# per-function facts collected in one AST walk
# ---------------------------------------------------------------------------

@dataclass
class _Acquire:
    lock: str                 # canonical lock id
    held: Tuple[str, ...]     # locks already held at this point
    line: int
    expr: str


@dataclass
class _Call:
    callees: Tuple[str, ...]  # candidate resolved qualnames
    held: Tuple[str, ...]
    line: int
    expr: str
    blocking: Optional[str] = None   # human label when the call blocks
    held_receiver: bool = False      # .wait() ON the held condition


@dataclass
class _FuncFacts:
    qualname: str             # module.Class.method or module.func
    relpath: str
    acquires: List[_Acquire] = field(default_factory=list)
    calls: List[_Call] = field(default_factory=list)
    # effects, filled by the fixpoint:
    may_acquire: Set[str] = field(default_factory=set)
    may_block: Dict[str, str] = field(default_factory=dict)  # label -> where


@dataclass
class _ThreadStart:
    relpath: str
    line: int
    scope: str
    target: str               # thread target expr (for the message)
    attr: Optional[str]       # stored attribute name (self.X = Thread(...))
    daemon: bool
    cls: Optional[str]        # owning class qualname, if a method


def _module_name(relpath: str) -> str:
    mod = relpath.replace(os.sep, "/")
    if mod.endswith(".py"):
        mod = mod[:-3]
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


class _FileScan(ast.NodeVisitor):
    """One pass over a file: lock definitions, per-function acquisition /
    call facts, thread constructions, join/daemon evidence."""

    def __init__(self, relpath: str, source: str, tree: ast.AST):
        self.relpath = relpath
        self.module = _module_name(relpath)
        self.supp = _suppressions(source)
        # lock ids defined here: attr name -> {owning class or module}
        # (prescanned so a use may precede the definition in source order
        # — `step()` above `__init__` in the class body)
        self.class_locks: Dict[str, Set[str]] = {}   # class -> attr names
        self.module_locks: Set[str] = set()
        self._prescan_locks(tree)
        self.funcs: Dict[str, _FuncFacts] = {}
        self.threads: List[_ThreadStart] = []
        # join/daemon evidence: (class qualname or "", attr name)
        self.joined_attrs: Set[Tuple[str, str]] = set()
        self.daemon_attrs: Set[Tuple[str, str]] = set()
        self.imports: Dict[str, str] = {}  # alias -> dotted module
        self._class_stack: List[str] = []
        self._func_stack: List[_FuncFacts] = []
        self._held: List[str] = []

    # -- plumbing ----------------------------------------------------------
    def _prescan_locks(self, tree: ast.AST) -> None:
        def walk(node, class_path: List[str], in_func: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, class_path + [child.name], in_func)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    walk(child, class_path, True)
                else:
                    if isinstance(child, ast.Assign) and \
                            self._is_lock_ctor(child.value):
                        for tgt in child.targets:
                            if (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"
                                    and class_path):
                                self.class_locks.setdefault(
                                    ".".join(class_path), set()).add(
                                        tgt.attr)
                            elif isinstance(tgt, ast.Name) and not in_func:
                                self.module_locks.add(tgt.id)
                    walk(child, class_path, in_func)

        walk(tree, [], False)

    def _cls(self) -> str:
        return ".".join(self._class_stack)

    def _qual(self, name: str) -> str:
        parts = [self.module] + self._class_stack + [name]
        return ".".join(parts)

    def visit_Import(self, node: ast.Import):
        for a in node.names:
            self.imports[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module or node.level:
            base = node.module or ""
            for a in node.names:
                self.imports[a.asname or a.name] = (
                    ("." * node.level) + base + "." + a.name
                    if base else ("." * node.level) + a.name)
        self.generic_visit(node)

    # -- lock definitions --------------------------------------------------
    @staticmethod
    def _is_lock_ctor(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in LOCK_FACTORIES:
            return True
        if isinstance(fn, ast.Name) and fn.id in LOCK_FACTORIES:
            return True
        return False

    def visit_Assign(self, node: ast.Assign):
        if self._is_lock_ctor(node.value):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self" and self._class_stack):
                    self.class_locks.setdefault(
                        self._cls(), set()).add(tgt.attr)
                elif isinstance(tgt, ast.Name) and not self._func_stack:
                    self.module_locks.add(tgt.id)
        # daemon evidence: self.X.daemon = True
        for tgt in node.targets:
            if (isinstance(tgt, ast.Attribute) and tgt.attr == "daemon"
                    and isinstance(tgt.value, ast.Attribute)
                    and isinstance(tgt.value.value, ast.Name)
                    and tgt.value.value.id == "self"):
                self.daemon_attrs.add((self._cls(), tgt.value.attr))
        self._maybe_thread_assign(node)
        self.generic_visit(node)

    # -- lock identity at a use site ---------------------------------------
    def _lock_id(self, node: ast.AST) -> Optional[str]:
        """Canonical id when ``node`` names a known lock, else None."""
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            cls = self._cls()
            if node.attr in self.class_locks.get(cls, ()):  # same class
                return f"{self.module}.{cls}.{node.attr}"
            return None
        if isinstance(node, ast.Name) and node.id in self.module_locks:
            return f"{self.module}.{node.id}"
        return None

    # -- function facts ----------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef):
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_func(self, node):
        facts = _FuncFacts(self._qual(node.name), self.relpath)
        self.funcs[facts.qualname] = facts
        self._func_stack.append(facts)
        saved_held, self._held = self._held, []
        self.generic_visit(node)
        self._held = saved_held
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_With(self, node: ast.With):
        pushed = []
        for item in node.items:
            lock = self._lock_id(item.context_expr)
            if lock is not None and self._func_stack:
                self._func_stack[-1].acquires.append(_Acquire(
                    lock, tuple(self._held), item.context_expr.lineno,
                    _unparse(item.context_expr)))
                self._held.append(lock)
                pushed.append(lock)
            else:
                # still walk the context expr for calls/locks inside it
                self.visit(item.context_expr)
                if item.optional_vars is not None:
                    self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in pushed:
            self._held.pop()

    visit_AsyncWith = visit_With

    # -- calls: acquire()/release(), blocking, thread ctor, callees --------
    def _callee_candidates(self, fn: ast.AST) -> Tuple[str, ...]:
        # self.m() -> same-class method
        if (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)):
            base, attr = fn.value.id, fn.attr
            if base == "self" and self._class_stack:
                return (f"{self.module}.{self._cls()}.{attr}",)
            target = self.imports.get(base)
            if target is not None:
                return (_resolve_import(self.module, target) + "." + attr,)
            return ()
        if isinstance(fn, ast.Name):
            # bare function in the same module, or imported symbol
            target = self.imports.get(fn.id)
            if target is not None:
                return (_resolve_import(self.module, target),)
            return (f"{self.module}.{fn.id}",
                    f"{self.module}.{fn.id}.__init__")
        return ()

    def _blocking_label(self, node: ast.Call) -> Tuple[Optional[str], bool]:
        """(label, is-held-receiver-wait) when this call blocks."""
        fn = node.func
        timeout_kw = any(
            kw.arg in ("timeout", "deadline", "timeout_s") or kw.arg is None
            for kw in node.keywords)
        if timeout_kw:
            # a bounded wait (subprocess.run(timeout=), wait(timeout=),
            # …) cannot wedge the lock holder indefinitely
            return None, False
        has_timeout = bool(node.args) or timeout_kw
        if isinstance(fn, ast.Attribute):
            if fn.attr in BLOCKING_ATTRS:
                # socket.recv(n) carries a size arg — args alone don't
                # make it non-blocking; only wait/join/get use timeouts
                return BLOCKING_ATTRS[fn.attr], False
            if fn.attr in COMPILE_ATTRS:
                recv_is_re = (isinstance(fn.value, ast.Name)
                              and fn.value.id in ("re", "regex"))
                str_lower = fn.attr == "lower" and not node.args \
                    and not node.keywords
                if not recv_is_re and not str_lower:
                    return COMPILE_ATTRS[fn.attr], False
            if fn.attr in BLOCKING_NO_TIMEOUT_ATTRS and not has_timeout:
                held_recv = self._lock_id(fn.value) in self._held \
                    if self._held else False
                return BLOCKING_NO_TIMEOUT_ATTRS[fn.attr], held_recv
            if fn.attr == "run" and isinstance(fn.value, ast.Name) \
                    and self.imports.get(fn.value.id, "") == "subprocess":
                return "subprocess wait", False
        elif isinstance(fn, ast.Name):
            target = self.imports.get(fn.id)
            if fn.id in BLOCKING_NAMES and BLOCKING_NAMES[fn.id]:
                if target in ("time.sleep", "socket.create_connection") \
                        or target is None:
                    return BLOCKING_NAMES[fn.id], False
        return None, False

    def visit_Call(self, node: ast.Call):
        fn = node.func
        facts = self._func_stack[-1] if self._func_stack else None
        # explicit .acquire() — treat as held to end of function scope
        # (the with-statement is the idiom; bare acquire is approximated)
        if isinstance(fn, ast.Attribute) and fn.attr == "acquire":
            lock = self._lock_id(fn.value)
            if lock is not None and facts is not None:
                facts.acquires.append(_Acquire(
                    lock, tuple(self._held), node.lineno, _unparse(fn)))
                if lock not in self._held:
                    self._held.append(lock)
        if isinstance(fn, ast.Attribute) and fn.attr == "release":
            lock = self._lock_id(fn.value)
            if lock is not None and lock in self._held:
                self._held.remove(lock)
        self._maybe_thread_call(node)
        if facts is not None:
            label, held_recv = self._blocking_label(node)
            if label and _suppressed(self.supp, "C002", node.lineno):
                # origin-site suppression: an annotated deliberate
                # block (e.g. the chaos delay action) must not taint
                # every lock-held caller through the fixpoint either
                label, held_recv = None, False
            facts.calls.append(_Call(
                self._callee_candidates(fn), tuple(self._held),
                node.lineno, _unparse(node), blocking=label,
                held_receiver=held_recv))
        self.generic_visit(node)

    # -- thread lifecycle --------------------------------------------------
    @staticmethod
    def _is_thread_ctor(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        fn = node.func
        return (isinstance(fn, ast.Attribute) and fn.attr == "Thread") or (
            isinstance(fn, ast.Name) and fn.id == "Thread")

    @staticmethod
    def _thread_kwargs(node: ast.Call) -> Tuple[bool, str]:
        daemon = False
        target = ""
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            if kw.arg == "target":
                target = _unparse(kw.value)
        return daemon, target

    def _maybe_thread_assign(self, node: ast.Assign):
        if not self._is_thread_ctor(node.value):
            return
        daemon, target = self._thread_kwargs(node.value)
        attr = None
        for tgt in node.targets:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                attr = tgt.attr
            elif isinstance(tgt, ast.Name):
                attr = tgt.id
        self.threads.append(_ThreadStart(
            self.relpath, node.value.lineno, self._scope_name(), target,
            attr, daemon, self._cls() or None))

    def _maybe_thread_call(self, node: ast.Call):
        # anonymous start: threading.Thread(...).start() or a bare ctor
        # call used as an expression / argument
        if self._is_thread_ctor(node):
            parent_handled = False
            # assignment-target ctors are handled in visit_Assign
            # (ast gives no parent pointer; detect by recording the line)
            for t in self.threads:
                if t.line == node.lineno and t.relpath == self.relpath:
                    parent_handled = True
            if not parent_handled:
                daemon, target = self._thread_kwargs(node)
                self.threads.append(_ThreadStart(
                    self.relpath, node.lineno, self._scope_name(), target,
                    None, daemon, self._cls() or None))
        fn = node.func
        # join evidence: self.X.join(...) / X.join(...)
        if isinstance(fn, ast.Attribute) and fn.attr == "join":
            obj = fn.value
            if (isinstance(obj, ast.Attribute)
                    and isinstance(obj.value, ast.Name)
                    and obj.value.id == "self"):
                self.joined_attrs.add((self._cls(), obj.attr))
            elif isinstance(obj, ast.Name):
                # a bare local only counts as joined within its own
                # function — `t.join()` elsewhere must not absolve
                # every thread that happens to be named `t`
                self.joined_attrs.add((f"scope:{self._scope_name()}",
                                       obj.id))

    def _scope_name(self) -> str:
        parts = list(self._class_stack)
        if self._func_stack:
            parts.append(self._func_stack[-1].qualname.split(".")[-1])
        return ".".join(parts) or "<module>"


def _resolve_import(module: str, target: str) -> str:
    """Resolve a (possibly relative) import target against ``module``."""
    if not target.startswith("."):
        return target
    level = len(target) - len(target.lstrip("."))
    base = module.split(".")
    base = base[: len(base) - level] if level <= len(base) else []
    rest = target.lstrip(".")
    return ".".join(base + ([rest] if rest else []))


# ---------------------------------------------------------------------------
# corpus analysis: fixpoint over call graph, lock-order graph, findings
# ---------------------------------------------------------------------------

class Analysis:
    """The whole-corpus concurrency model tpulint queries."""

    def __init__(self):
        self.funcs: Dict[str, _FuncFacts] = {}
        self.threads: List[_ThreadStart] = []
        self.joined: Set[Tuple[str, str]] = set()
        self.daemon: Set[Tuple[str, str]] = set()
        self.supp: Dict[str, Dict[int, Set[str]]] = {}
        # lock-order graph: (a, b) -> list of (relpath, line, via)
        self.edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}

    # -- interprocedural effects ------------------------------------------
    def _fixpoint(self):
        for _ in range(_MAX_DEPTH):
            changed = False
            for facts in self.funcs.values():
                acq = {a.lock for a in facts.acquires}
                blk = {}
                for c in facts.calls:
                    if c.blocking and not c.held_receiver:
                        blk.setdefault(
                            c.blocking, f"{facts.relpath}:{c.line}")
                    for callee in c.callees:
                        callee_facts = self._lookup(callee)
                        if callee_facts is None:
                            continue
                        acq |= callee_facts.may_acquire
                        for label, where in callee_facts.may_block.items():
                            blk.setdefault(label, where)
                if acq - facts.may_acquire:
                    facts.may_acquire |= acq
                    changed = True
                for label, where in blk.items():
                    if label not in facts.may_block:
                        facts.may_block[label] = where
                        changed = True
            if not changed:
                break

    def _lookup(self, qualname: str) -> Optional[_FuncFacts]:
        facts = self.funcs.get(qualname)
        if facts is not None:
            return facts
        # Class(...) resolves to Class.__init__
        return self.funcs.get(qualname + ".__init__")

    def _add_edge(self, a: str, b: str, relpath: str, line: int, via: str):
        if a == b:
            return  # RLock re-entry / same-lock nesting is not an order
        self.edges.setdefault((a, b), []).append((relpath, line, via))

    def build(self):
        self._fixpoint()
        for facts in self.funcs.values():
            for acq in facts.acquires:
                for held in acq.held:
                    self._add_edge(held, acq.lock, facts.relpath, acq.line,
                                   f"direct in {facts.qualname}")
            for call in facts.calls:
                if not call.held:
                    continue
                for callee in call.callees:
                    callee_facts = self._lookup(callee)
                    if callee_facts is None:
                        continue
                    for lock in callee_facts.may_acquire:
                        for held in call.held:
                            self._add_edge(
                                held, lock, facts.relpath, call.line,
                                f"via {callee_facts.qualname}")

    # -- cycles ------------------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the lock-order graph (deduped by the
        cycle's canonical rotation)."""
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        seen: Set[Tuple[str, ...]] = set()
        out: List[List[str]] = []

        def dfs(start: str, node: str, path: List[str],
                visited: Set[str]):
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    canon = _canonical(path)
                    if canon not in seen:
                        seen.add(canon)
                        out.append(list(path))
                elif nxt not in visited and len(path) < 8:
                    visited.add(nxt)
                    path.append(nxt)
                    dfs(start, nxt, path, visited)
                    path.pop()
                    visited.discard(nxt)

        for start in sorted(graph):
            dfs(start, start, [start], {start})
        return out


def _canonical(cycle: Sequence[str]) -> Tuple[str, ...]:
    i = cycle.index(min(cycle))
    return tuple(cycle[i:]) + tuple(cycle[:i])


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def scan_paths(paths: Sequence[str], root: Optional[str] = None
               ) -> Analysis:
    root = root or os.getcwd()
    ana = Analysis()
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root)
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue  # ast_rules reports A000 for this file
        scan = _FileScan(rel, text, tree)
        scan.visit(tree)
        ana.funcs.update(scan.funcs)
        ana.threads.extend(scan.threads)
        ana.joined |= scan.joined_attrs
        ana.daemon |= scan.daemon_attrs
        ana.supp[rel] = scan.supp
    ana.build()
    return ana


def _sup(ana: Analysis, rule: str, relpath: str, line: int) -> bool:
    return _suppressed(ana.supp.get(relpath, {}), rule, line)


def lint_paths(paths: Sequence[str], root: Optional[str] = None
               ) -> List[Finding]:
    """Run C001/C002/C003 over files/directories."""
    ana = scan_paths(paths, root=root)
    findings: List[Finding] = []
    findings.extend(_c001(ana))
    findings.extend(_c002(ana))
    findings.extend(_c003(ana))
    return findings


def _c001(ana: Analysis) -> List[Finding]:
    out: List[Finding] = []
    for cycle in ana.cycles():
        ring = cycle + [cycle[0]]
        sites = []
        suppressed = False
        for a, b in zip(ring, ring[1:]):
            occ = ana.edges.get((a, b))
            if occ:
                rel, line, via = occ[0]
                sites.append(f"{a}->{b} ({rel}:{line} {via})")
                if _sup(ana, "C001", rel, line):
                    suppressed = True
        if suppressed:
            continue
        rel, line = "", 0
        first = ana.edges.get((ring[0], ring[1]))
        if first:
            rel, line, _ = first[0]
        out.append(Finding(
            "C001",
            "lock-order cycle (potential deadlock): "
            + " -> ".join(ring),
            path=rel, line=line, scope="lock-graph",
            detail="cycle:" + "->".join(_canonical(cycle)),
            hint="pick one global order for these locks, or release the "
                 "outer lock before taking the inner one; edges: "
                 + "; ".join(sites)))
    return out


def _c002(ana: Analysis) -> List[Finding]:
    out: List[Finding] = []
    for facts in ana.funcs.values():
        for call in facts.calls:
            if not call.held:
                continue
            label = call.blocking
            if label and not call.held_receiver:
                if _sup(ana, "C002", facts.relpath, call.line):
                    continue
                out.append(Finding(
                    "C002",
                    f"blocking call ({label}) while holding "
                    f"{_short(call.held[-1])}: `{call.expr}`",
                    path=facts.relpath, line=call.line,
                    scope=_scope_of(facts.qualname),
                    detail=f"block:{label}:{_short(call.held[-1])}"
                           f":{call.expr[:40]}",
                    hint="move the blocking work outside the lock "
                         "(snapshot state under the lock, then block), "
                         "or bound it with a timeout"))
                continue
            # interprocedural: callee blocks while we hold a lock
            for callee in call.callees:
                cf = ana._lookup(callee)
                if cf is None or not cf.may_block:
                    continue
                if _sup(ana, "C002", facts.relpath, call.line):
                    continue
                blabel, where = next(iter(sorted(cf.may_block.items())))
                out.append(Finding(
                    "C002",
                    f"call into `{_short(callee)}` which blocks "
                    f"({blabel}, {where}) while holding "
                    f"{_short(call.held[-1])}",
                    path=facts.relpath, line=call.line,
                    scope=_scope_of(facts.qualname),
                    detail=f"block-via:{_short(callee)}:{blabel}"
                           f":{_short(call.held[-1])}",
                    hint="hoist the call out of the locked region or "
                         "split the callee so its blocking half runs "
                         "lock-free"))
                break
    return out


def _c003(ana: Analysis) -> List[Finding]:
    out: List[Finding] = []
    for t in ana.threads:
        if t.daemon:
            continue
        owner = t.cls or ""
        if t.attr is not None:
            if (owner, t.attr) in ana.joined \
                    or (f"scope:{t.scope}", t.attr) in ana.joined:
                continue
            if (owner, t.attr) in ana.daemon:
                continue
        if _sup(ana, "C003", t.relpath, t.line):
            continue
        what = f"target={t.target}" if t.target else "thread"
        handle = f"self.{t.attr}" if t.attr and t.cls else (t.attr or
                                                           "<anonymous>")
        out.append(Finding(
            "C003",
            f"non-daemon Thread ({what}) stored as {handle} is never "
            "joined — leaks one thread per start and can hang "
            "interpreter shutdown",
            path=t.relpath, line=t.line, scope=t.scope,
            detail=f"thread:{handle}:{t.target[:40]}",
            hint="pass daemon=True, or keep a stop event + join() the "
                 "handle in the owner's close()/stop() path"))
    return out


def _short(qual: str) -> str:
    parts = qual.split(".")
    return ".".join(parts[-2:]) if len(parts) > 2 else qual


def _scope_of(qualname: str) -> str:
    parts = qualname.split(".")
    return ".".join(parts[-2:]) if len(parts) >= 2 else qualname


__all__ = ["lint_paths", "scan_paths", "Analysis"]
