"""``mx.analysis`` — tpulint, the TPU anti-pattern analyzer.

Three layers, one finding model (:class:`~.findings.Finding`):

- :mod:`.jaxpr_rules` — trace a block/callable with ``jax.make_jaxpr``
  and lint the IR: MXU tile alignment, float64 leakage, dtype churn,
  scalar-reduce outputs, donation misses (J001–J005).
- :mod:`.ast_rules` — lint Python source: host syncs in hot paths,
  jit-cache-key hazards, f64 literals (A001–A003), with
  ``# tpulint: disable=<rule>`` inline suppression.
- :mod:`.sentinel` — opt-in runtime watch (``MXNET_TPU_LINT``):
  counts jit cache misses and device->host transfers through
  ``mx.profiler`` and warns/raises past a budget.
- :mod:`.opt` — the transform arm: cost-model-gated jaxpr rewrites
  (J001 pad-to-tile, J003 churn elimination), an analytic TPU cost
  model calibrated on the banked bench corpus, and a knob autotuner
  emitting fingerprint-keyed ``TunedConfig``s (``MXNET_TPU_OPT``).
- :mod:`.concurrency` — the C-rules: interprocedural lock-order graph
  with cycle detection (C001), blocking-under-lock (C002), thread-
  lifecycle leaks (C003) — the bug classes the cluster PRs kept
  finding by hand.
- :mod:`.lockwatch` — runtime witness for the C-rules
  (``MXNET_TPU_LOCKWATCH``): wraps lock factories to record the
  observed acquisition order and assert acyclicity inside drills.
- :mod:`.contracts` — the R-rules: swallowed faults in retry paths
  (R001), untyped raises under the TransientError/FatalError taxonomy
  (R002), and three-way code↔docs drift gates for chaos sites,
  ``MXNET_TPU_*`` env vars and telemetry series (R003).

``tools/tpulint.py`` is the CLI; the tier-1 suite self-lints the
framework against ``tools/tpulint_baseline.json`` so new high-severity
findings fail CI. Full catalog: ``docs/static_analysis.md``.
"""
from __future__ import annotations

import os as _os

from .findings import Finding, RULES, sort_findings, max_severity  # noqa: F401
from .ast_rules import lint_source, lint_paths, cache_key_knobs  # noqa: F401
from .jaxpr_rules import (  # noqa: F401
    lint_jaxpr,
    lint_callable,
    lint_block,
    find_donation_misses,
    lint_trainer,
)
from . import baseline  # noqa: F401
from . import concurrency  # noqa: F401
from . import contracts  # noqa: F401
from . import lockwatch  # noqa: F401
from . import opt  # noqa: F401
from . import sentinel  # noqa: F401
from .sentinel import TpuLintWarning, LintBudgetExceeded  # noqa: F401

__all__ = [
    "Finding", "RULES", "sort_findings", "max_severity",
    "lint_source", "lint_paths", "cache_key_knobs",
    "lint_jaxpr", "lint_callable", "lint_block",
    "find_donation_misses", "lint_trainer",
    "baseline", "concurrency", "contracts", "lockwatch",
    "opt", "sentinel", "TpuLintWarning",
    "LintBudgetExceeded",
]

if _os.environ.get("MXNET_TPU_LINT"):
    sentinel.activate_from_env()
