"""Cost-model-pruned autotuner over the repo's discrete knob space.

TVM (arXiv:1802.04799) demonstrated the loop this module implements:
enumerate a discrete schedule space, let a cost model rank it, confirm
the survivors with short timed probes, persist the winner. The knobs
here are the ones the repo already exposes end to end:

- ``steps_per_launch`` — serial ``lax.scan`` chaining inside one
  executable (``train_bench --scan-steps``; amortizes the ~4.5 ms
  tunnel launch),
- ``stem_s2d`` — the conv-stem space-to-depth rewrite knob
  (``MXNET_TPU_STEM_S2D``),
- ``remat`` — rematerialize the forward in backward
  (``jax.checkpoint`` around the loss),
- serving ``bucket_sizes`` / ``max_delay_ms`` — the engine ladder.

The winner is a :class:`TunedConfig` persisted under
``MXNET_TPU_OPT_DIR`` (default: ``<MXNET_TPU_AOT_CACHE>/tuned`` when
the AOT store is armed), **fingerprint-keyed via** :func:`aot.fingerprint`
— the same key that folds in the jaxpr hash, avals, backend, jax/jaxlib
versions and the A002 env-knob signature, so a knob flip or a jaxlib
upgrade invalidates a stale config instead of silently applying it.
``gluon.Trainer(tuned=…)`` and ``serving.InferenceEngine(tuned=…)``
consume configs at build time (:meth:`TunedConfig.for_trainer` /
knob accessors), and every probe lands in the telemetry registry
(``opt_tune_*``)."""
from __future__ import annotations

import itertools
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .cost_model import CostModel

__all__ = ["TunedConfig", "KnobSpace", "autotune", "store_dir",
           "load_tuned", "lookup", "DEFAULT_SPACE"]

#: the default discrete space — every knob is one the repo already
#: consumes (docs/auto_opt.md lists the consumption sites)
DEFAULT_SPACE: Dict[str, Tuple] = {
    "steps_per_launch": (1, 2, 4, 8, 16, 32),
}

KnobSpace = Dict[str, Tuple]


def store_dir() -> Optional[str]:
    """Where tuned configs persist: ``MXNET_TPU_OPT_DIR``, else
    ``<MXNET_TPU_AOT_CACHE>/tuned`` when the AOT store is armed, else
    None (tuning still works, nothing persists)."""
    env = os.environ.get("MXNET_TPU_OPT_DIR")
    if env:
        return env
    aot_dir = os.environ.get("MXNET_TPU_AOT_CACHE")
    if aot_dir:
        return os.path.join(aot_dir, "tuned")
    return None


@dataclass
class TunedConfig:
    """A persisted tuning verdict: the chosen knobs plus the full
    provenance needed to (a) refuse to apply itself when stale and
    (b) justify itself in a bench row."""
    label: str
    key: str                      # aot.fingerprint hex over the probe fn
    knobs: Dict[str, Any]
    predicted_ms: Optional[float] = None
    measured_ms: Optional[float] = None
    baseline_ms: Optional[float] = None
    probes: int = 0
    tune_spend_s: float = 0.0
    backend: str = ""
    device_kind: str = ""
    jax_version: str = ""
    jaxlib_version: str = ""
    knob_signature: List = field(default_factory=list)
    created_unix: float = 0.0
    candidates: List[Dict] = field(default_factory=list)
    #: resolved mesh axis map at tune time ({"dp": 8, ...}; None =
    #: tuned off-mesh). Part of the config identity: a knob verdict
    #: probed at dp=8 says nothing about dp=256 — collective shapes,
    #: per-device batch and launch overheads all change with the mesh.
    mesh_axes: Optional[Dict[str, int]] = None

    # -- persistence ------------------------------------------------------
    def filename(self) -> str:
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in self.label)
        return f"{safe}-{self.key[:16]}.json"

    def save(self, directory: Optional[str] = None) -> Optional[str]:
        directory = directory or store_dir()
        if not directory:
            return None
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, self.filename())
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)  # atomic publish (CheckpointManager rule)
        return path

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in (
            "label", "key", "knobs", "predicted_ms", "measured_ms",
            "baseline_ms", "probes", "tune_spend_s", "backend",
            "device_kind", "jax_version", "jaxlib_version",
            "knob_signature", "created_unix", "candidates",
            "mesh_axes")}

    @classmethod
    def from_dict(cls, d: dict) -> "TunedConfig":
        return cls(**{k: d.get(k) for k in (
            "label", "key", "knobs", "predicted_ms", "measured_ms",
            "baseline_ms", "probes", "tune_spend_s", "backend",
            "device_kind", "jax_version", "jaxlib_version",
            "knob_signature", "created_unix", "candidates",
            "mesh_axes")
            if d.get(k) is not None} | {"label": d["label"],
                                        "key": d["key"],
                                        "knobs": d["knobs"]})

    # -- staleness --------------------------------------------------------
    def is_current(self) -> bool:
        """True while the environment still matches the one that tuned
        this config: jax/jaxlib versions and the live A002 knob
        signature. A consumer must treat a stale config as absent —
        warn once and fall back to defaults, never apply blindly."""
        from ...aot import knob_signature
        from ...aot.cache import jaxlib_version
        import jax

        if self.jaxlib_version and self.jaxlib_version != jaxlib_version():
            return False
        if self.jax_version and self.jax_version != jax.__version__:
            return False
        if self.knob_signature:
            live = [[k, v] for k, v in knob_signature()]
            if [list(p) for p in self.knob_signature] != live:
                return False
        if self._live_mesh_axes() != (
                dict(self.mesh_axes) if self.mesh_axes else None):
            # tuned under one mesh, consumed under another (or tuned
            # off-mesh, consumed on one): stale — a dp=8 verdict must
            # never be applied at dp=256
            return False
        return True

    @staticmethod
    def _live_mesh_axes() -> Optional[Dict[str, int]]:
        try:
            from ...parallel.sharding import mesh_topology

            topo = mesh_topology()
        except Exception:  # noqa: BLE001
            return None
        return dict(topo["axes"]) if topo else None

    def provenance(self) -> dict:
        """The compact dict bench rows embed (tuned-config provenance
        in ``train_bench`` / ``serve_bench``)."""
        return {"label": self.label, "key": self.key[:16],
                "knobs": self.knobs, "measured_ms": self.measured_ms,
                "predicted_ms": self.predicted_ms,
                "created_unix": self.created_unix}


def load_tuned(path: str) -> TunedConfig:
    with open(path) as f:
        return TunedConfig.from_dict(json.load(f))


def fingerprint_key(fn: Callable, example_args, label: str,
                    space: Optional[KnobSpace] = None) -> str:
    """The config identity: :func:`aot.fingerprint` of the *reference*
    (knob-default) program + the knob space searched. Everything that
    must invalidate a config — program change, aval change, backend,
    jax/jaxlib, env-knob flips — is already inside the fingerprint."""
    from ...aot import fingerprint

    extra = [json.dumps({k: list(v) for k, v in sorted(
        (space or {}).items())}, sort_keys=True)]
    key, _ = fingerprint(fn, example_args, label=f"opt.tune/{label}",
                         extra=extra)
    return key


def lookup(label: str, fn: Callable = None, example_args=None,
           space: Optional[KnobSpace] = None,
           directory: Optional[str] = None) -> Optional[TunedConfig]:
    """Load the persisted config for ``label`` **iff it is still
    valid**: the stored key must equal the freshly computed fingerprint
    (when ``fn``/``example_args`` are given) and :meth:`is_current`
    must hold. Returns None otherwise — a miss, never a stale apply."""
    directory = directory or store_dir()
    if not directory or not os.path.isdir(directory):
        return None
    want_key = None
    if fn is not None and example_args is not None:
        want_key = fingerprint_key(fn, example_args, label, space)
    best = None
    for name in sorted(os.listdir(directory)):
        if not name.endswith(".json"):
            continue
        try:
            cfg = load_tuned(os.path.join(directory, name))
        except (OSError, ValueError, KeyError, TypeError):
            continue
        if cfg.label != label:
            continue
        if want_key is not None and cfg.key != want_key:
            continue
        if not cfg.is_current():
            continue
        if best is None or cfg.created_unix > best.created_unix:
            best = cfg
    return best


# -- telemetry --------------------------------------------------------------
def _gauges():
    from ...telemetry import get_registry

    reg = get_registry()
    return {
        "probe_ms": reg.gauge(
            "opt_tune_probe_ms",
            "Measured ms/step of the latest autotune probe",
            ("label", "config")),
        "best_ms": reg.gauge(
            "opt_tune_best_ms", "Winning measured ms/step", ("label",)),
        "predicted_ms": reg.gauge(
            "opt_tune_predicted_ms",
            "Cost-model predicted ms/step of the winner", ("label",)),
        "probes": reg.counter(
            "opt_tune_probes_total", "Timed autotune probes", ("label",)),
        "spend_s": reg.gauge(
            "opt_tune_spend_s",
            "Wall seconds spent probing in the last tune", ("label",)),
    }


def _knob_id(knobs: Dict[str, Any]) -> str:
    return ",".join(f"{k}={knobs[k]}" for k in sorted(knobs))


def autotune(builder: Callable[..., Tuple[Callable, tuple]], *,
             label: str,
             space: Optional[KnobSpace] = None,
             model: Optional[CostModel] = None,
             probe_top_k: int = 3,
             probe_reps: int = 3,
             min_probe_wall_s: float = 0.05,
             warmup_reps: int = 1,
             budget_s: Optional[float] = None,
             steps_per_probe_knob: str = "steps_per_launch",
             timer: Callable[[], float] = time.perf_counter,
             save: bool = True,
             directory: Optional[str] = None,
             log=None) -> TunedConfig:
    """Search ``space`` for the fastest configuration of ``builder``.

    ``builder(**knobs)`` returns ``(step_fn, args)``; one *probe* calls
    ``step_fn(*args)`` and blocks on the result. The search is the TVM
    loop shrunk to the repo's knob count: the **cost model ranks every
    candidate first** (tracing only — no compile), the top
    ``probe_top_k`` get ``probe_reps`` timed probes each (after
    ``warmup_reps`` untimed compile/warm calls), and the best measured
    median wins. ``budget_s`` bounds total probe wall time: when
    exceeded, remaining candidates keep their cost-model ranking and
    the best *measured* one wins (never an unmeasured candidate).

    Deterministic by construction: candidates enumerate in sorted knob
    order, ties break toward the earlier candidate, and the ``timer``
    is injectable (tests pin a fake clock; the tier-1 determinism test
    runs the whole loop twice and asserts identical verdicts).

    Returns the persisted (``save=True`` + a store dir) or in-memory
    :class:`TunedConfig`.
    """
    import jax

    space = dict(space or DEFAULT_SPACE)
    model = model or CostModel.for_backend()
    # an explicit caller budget wins; the env knob only fills the
    # default, and a typo'd value warns instead of killing the tune
    # (the MXNET_TPU_PREFLIGHT='5s' lesson)
    if budget_s is not None:
        budget = float(budget_s)
    else:
        from ...base import env_float

        budget = env_float("MXNET_TPU_OPT_TUNE_BUDGET_S", 60.0)
    gauges = _gauges()
    names = sorted(space)
    combos = [dict(zip(names, vals)) for vals in
              itertools.product(*(space[n] for n in names))]

    # 1) cost-model ranking (trace each candidate, no compile)
    ranked: List[Tuple[float, int, Dict[str, Any], Callable, tuple]] = []
    for idx, knobs in enumerate(combos):
        step_fn, args = builder(**knobs)
        spl = int(knobs.get(steps_per_probe_knob, 1))
        try:
            est = model.estimate_callable(step_fn, *args,
                                          steps_per_launch=1)
            # the builder's program already contains the scan chain, so
            # its per-launch estimate covers spl steps; normalize /step
            pred = (est.t_ops_s + model.launch_overhead_us * 1e-6) / spl
        except Exception as e:  # noqa: BLE001 — unrankable: probe last
            if log:
                log(f"autotune[{label}]: cost model failed for "
                    f"{_knob_id(knobs)}: {e!r}")
            pred = float("inf")
        ranked.append((pred, idx, knobs, step_fn, args))
    ranked.sort(key=lambda t: (t[0], t[1]))

    # 2) timed probes over the cost-model survivors — PLUS the
    # all-defaults combo, always and FIRST: the tuner must never crown
    # a config it didn't measure against the measured default (the
    # no-regression floor), and probing defaults first keeps that
    # guarantee even when the budget expires mid-loop
    probe_set = list(ranked[:max(1, probe_top_k)])
    defaults = {n: space[n][0] for n in names}
    probe_set = ([r for r in ranked if r[2] == defaults]
                 + [r for r in probe_set if r[2] != defaults])
    t_start = timer()
    results: List[Dict] = []
    best: Optional[Dict] = None
    for pred, idx, knobs, step_fn, args in probe_set:
        spent = timer() - t_start
        if results and budget and spent > budget:
            if log:
                log(f"autotune[{label}]: budget {budget:.1f}s exhausted "
                    f"after {len(results)} candidates")
            break
        spl = int(knobs.get(steps_per_probe_knob, 1))
        try:
            for _ in range(max(0, warmup_reps)):
                jax.block_until_ready(step_fn(*args))
            times = []
            for _ in range(max(1, probe_reps)):
                # each rep loops until a minimum wall so a sub-ms step
                # is measured above timer/scheduler noise — a 4 ms
                # single-launch sample on a busy host will happily
                # crown the wrong candidate (observed)
                launches, t0 = 0, timer()
                while True:
                    jax.block_until_ready(step_fn(*args))
                    launches += 1
                    dt = timer() - t0
                    if dt >= min_probe_wall_s or launches >= 1000:
                        break
                times.append(dt / launches)
            med = sorted(times)[len(times) // 2] / spl
        except Exception as e:  # noqa: BLE001 — a broken candidate loses
            if log:
                log(f"autotune[{label}]: probe failed for "
                    f"{_knob_id(knobs)}: {e!r}")
            continue
        gauges["probe_ms"].labels(
            label=label, config=_knob_id(knobs)).set(med * 1e3)
        gauges["probes"].labels(label=label).inc(len(times))
        row = {"knobs": knobs, "predicted_ms": None if pred == float(
            "inf") else round(pred * 1e3, 4),
            "measured_ms": round(med * 1e3, 4), "probes": len(times)}
        results.append(row)
        if best is None or med < best["_med"]:
            best = {**row, "_med": med}
    spend = timer() - t_start
    gauges["spend_s"].labels(label=label).set(spend)

    if best is None:
        raise RuntimeError(
            f"autotune[{label}]: every probed candidate failed")
    gauges["best_ms"].labels(label=label).set(best["_med"] * 1e3)
    if best.get("predicted_ms") is not None:
        gauges["predicted_ms"].labels(label=label).set(
            best["predicted_ms"])

    # the reference (all-defaults) row for the speedup bookkeeping
    baseline_row = next(
        (r for r in results
         if all(r["knobs"][n] == space[n][0] for n in names)), None)

    from ...aot import knob_signature
    from ...aot.cache import jaxlib_version

    ref_fn, ref_args = builder(**{n: space[n][0] for n in names})
    cfg = TunedConfig(
        label=label,
        key=fingerprint_key(ref_fn, ref_args, label, space),
        knobs=best["knobs"],
        predicted_ms=best.get("predicted_ms"),
        measured_ms=best["measured_ms"],
        baseline_ms=baseline_row["measured_ms"] if baseline_row else None,
        probes=sum(r["probes"] for r in results),
        tune_spend_s=round(spend, 3),
        backend=model.backend,
        device_kind=model.device_kind,
        jax_version=jax.__version__,
        jaxlib_version=jaxlib_version(),
        knob_signature=[list(p) for p in knob_signature()],
        created_unix=time.time(),
        candidates=results,
        mesh_axes=TunedConfig._live_mesh_axes(),
    )
    if log:
        log(f"autotune[{label}]: chose {_knob_id(cfg.knobs)} "
            f"({cfg.measured_ms:.3f} ms/step measured, "
            f"{cfg.probes} probes, {spend:.2f}s)")
    if save:
        cfg.save(directory)
    return cfg
