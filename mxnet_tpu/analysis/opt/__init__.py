"""``mx.analysis.opt`` — cost-model-guided auto-optimization.

tpulint's **transform arm**: where :mod:`mxnet_tpu.analysis` detects
TPU anti-patterns, this subpackage fixes the mechanical ones and tunes
the knobs around them, converting the lint baseline from a debt ledger
into a work queue. Three layers (see ``docs/auto_opt.md``):

- :mod:`.cost_model` — analytic roofline over padded-tile FLOPs,
  dtype-aware HBM bytes and launch overhead (arXiv:2008.01040's
  feature set, analytic instead of learned), calibrated against the
  banked ``benchmark/results_*.json`` TPU corpus
  (:mod:`.calibration`; rank fidelity is a tier-1 test).
- :mod:`.rewrites` — jaxpr rewrite passes: J001 pad-to-MXU-tile and
  J003 exact convert-churn cancellation, each gated by a cost-model
  predicted win and verified by the interpret-mode equivalence oracle
  (:func:`check_equivalence`).
- :mod:`.autotune` — TVM-style search over the repo's discrete knob
  space (``steps_per_launch``, serving buckets, remat, stem-s2d):
  cost-model pruning + short timed probes, persisting a
  fingerprint-keyed :class:`TunedConfig` that ``gluon.Trainer`` and
  ``serving.InferenceEngine`` consume at build time.

Mode knob: ``MXNET_TPU_OPT=off|advise|rewrite`` (default ``advise`` —
plan and report, transform only when explicitly asked).
"""
from __future__ import annotations

from .cost_model import (  # noqa: F401
    CostEstimate,
    CostModel,
    OpCost,
    OpFeatures,
    extract_features,
    spearman,
)
from .rewrites import (  # noqa: F401
    RewriteDecision,
    RewriteReport,
    check_equivalence,
    mode,
    rewrite_block,
    rewrite_callable,
)
from .autotune import (  # noqa: F401
    DEFAULT_SPACE,
    KnobSpace,
    TunedConfig,
    autotune,
    load_tuned,
    lookup,
    store_dir,
)
from . import calibration  # noqa: F401


def record_prediction(name: str, predicted_s, observed_s=None) -> dict:
    """Land a predicted-vs-observed step time in the telemetry registry
    (``opt_predicted_step_ms`` / ``opt_observed_step_ms`` gauges, plus
    the ratio) — how a bench row or a tuned training loop exposes
    whether the cost model still tracks reality. Returns the values as
    a dict for embedding in bench rows."""
    from ...telemetry import get_registry

    reg = get_registry()
    out = {}
    if predicted_s is not None:
        reg.gauge("opt_predicted_step_ms",
                  "Cost-model predicted step time", ("name",)).labels(
            name=name).set(predicted_s * 1e3)
        out["predicted_ms"] = round(predicted_s * 1e3, 4)
    if observed_s is not None:
        reg.gauge("opt_observed_step_ms",
                  "Measured step time next to its prediction",
                  ("name",)).labels(name=name).set(observed_s * 1e3)
        out["observed_ms"] = round(observed_s * 1e3, 4)
    if predicted_s and observed_s:
        out["predicted_over_observed"] = round(
            predicted_s / observed_s, 3)
    return out

__all__ = [
    "CostEstimate", "CostModel", "OpCost", "OpFeatures",
    "extract_features", "spearman",
    "RewriteDecision", "RewriteReport", "check_equivalence", "mode",
    "rewrite_block", "rewrite_callable",
    "DEFAULT_SPACE", "KnobSpace", "TunedConfig", "autotune",
    "load_tuned", "lookup", "store_dir",
    "calibration", "record_prediction",
]
