"""Cost-model-gated jaxpr rewrite passes — tpulint's transform arm.

tpulint (PR 3) can *see* the TPU anti-patterns in a traced program;
this module *fixes* the two mechanical ones, producing a semantically
equivalent callable:

- **J001 pad-to-MXU-tile** — ``dot_general`` / ``conv_general_dilated``
  operands whose M/K/N (or C_in/C_out) dims pad badly against the
  (sublane=8, lane=128) register tiles are zero-padded up to tile
  multiples and the result sliced back. Zero-padding a contraction is
  *exact* (zero taps contribute zero) and the pad/slice live inside the
  traced program, where XLA fuses them into the producing/consuming
  loops instead of materializing relayouts at every op boundary.
- **J003 convert-churn elimination** — ``A -> B -> A``
  ``convert_element_type`` round-trips are cancelled **only when B can
  exactly represent every value of A** (widening round-trips:
  ``bf16 -> f32 -> bf16``, ``int8 -> int32 -> int8``…), which makes the
  cancellation bit-exact. Lossy round-trips (``f32 -> bf16 -> f32``)
  are *reported but kept* — removing them would change numerics, and
  the equivalence oracle would rightly refuse the rewrite.

Every candidate is **gated by the cost model** (:mod:`.cost_model`):
a rewrite predicted as a loss on the target backend is refused and the
refusal is part of the report (J001 on a CPU target is the canonical
refusal: there is no tile relayout to save, only extra multiplies to
pay). Applied rewrites are verified by :func:`check_equivalence` — the
interpret-mode oracle ``benchmark/opt_bench.py`` and ``tests/test_opt``
run on every transformed program (bitwise for integer/bool outputs,
dtype-scaled tolerance for floats, where only the reduction *order*
may differ).

The transform itself is a jaxpr re-interpreter: the traced program is
replayed primitive-by-primitive through live jax ops (so the rewritten
callable jits, grads and vmaps like any other function), with planned
equations replaced by their padded/cancelled forms and ``pjit`` bodies
inlined (semantically a no-op under an enclosing jit).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as onp

from ..findings import Finding
from ..jaxpr_rules import TILE_LANE, TILE_SUBLANE, _misaligned
from .cost_model import CostModel, _pad_up, np_dtype

__all__ = ["RewriteDecision", "RewriteReport", "rewrite_callable",
           "rewrite_block", "check_equivalence", "mode"]

_VALID_MODES = ("off", "advise", "rewrite")


def mode(override: Optional[str] = None) -> str:
    """The auto-opt mode: ``MXNET_TPU_OPT`` = ``off`` (plan nothing) |
    ``advise`` (plan + report, transform only when explicitly asked) |
    ``rewrite`` (integration points transform too). Default: advise."""
    val = (override or os.environ.get("MXNET_TPU_OPT") or "advise")
    val = val.strip().lower()
    if val not in _VALID_MODES:
        import warnings

        warnings.warn(
            f"MXNET_TPU_OPT={val!r} is not one of {_VALID_MODES}; "
            "using 'advise'", RuntimeWarning, stacklevel=2)
        return "advise"
    return val


# -- telemetry --------------------------------------------------------------
def _counters():
    from ...telemetry import get_registry

    reg = get_registry()
    return (
        reg.counter("opt_rewrites_applied_total",
                    "Rewrites applied by mxnet_tpu.analysis.opt",
                    ("rule",)),
        reg.counter("opt_rewrites_refused_total",
                    "Rewrites planned but refused (cost model predicted "
                    "a loss, or the transform would change numerics)",
                    ("rule",)),
    )


# ---------------------------------------------------------------------------
# decisions
# ---------------------------------------------------------------------------
@dataclass
class RewriteDecision:
    """One planned (or refused) transformation of one equation."""
    rule: str                      # "J001" | "J003"
    path: Tuple[int, ...]          # eqn index path (nested via pjit)
    kind: str                      # pad_dot | pad_conv | cancel_convert
    detail: str
    applied: bool
    predicted_gain_s: float
    note: str = ""
    payload: Dict[str, Any] = field(default_factory=dict)

    def render(self) -> str:
        verdict = "apply " if self.applied else "refuse"
        gain = self.predicted_gain_s * 1e6
        return (f"{verdict} {self.rule}/{self.kind} @eqn{list(self.path)} "
                f"{self.detail}: predicted {gain:+.1f} us/step"
                + (f" ({self.note})" if self.note else ""))


@dataclass
class RewriteReport:
    """What the pass did and why — every apply/refuse carries its
    cost-model justification (`docs/auto_opt.md` anatomy)."""
    mode: str
    backend: str
    applied: List[RewriteDecision] = field(default_factory=list)
    refused: List[RewriteDecision] = field(default_factory=list)
    predicted_gain_s: float = 0.0
    scope: str = ""

    @property
    def n_applied(self) -> int:
        return len(self.applied)

    def decisions(self) -> List[RewriteDecision]:
        return self.applied + self.refused

    def render(self) -> str:
        head = (f"opt.rewrite[{self.scope or 'callable'}] target="
                f"{self.backend}: {len(self.applied)} applied, "
                f"{len(self.refused)} refused, predicted "
                f"{self.predicted_gain_s * 1e6:+.1f} us/step")
        return "\n".join([head] + [
            "  " + d.render() for d in self.decisions()])

    def to_dict(self) -> dict:
        return {
            "mode": self.mode, "backend": self.backend,
            "scope": self.scope,
            "predicted_gain_us": round(self.predicted_gain_s * 1e6, 2),
            "applied": [{"rule": d.rule, "kind": d.kind,
                         "detail": d.detail,
                         "predicted_gain_us":
                             round(d.predicted_gain_s * 1e6, 2)}
                        for d in self.applied],
            "refused": [{"rule": d.rule, "kind": d.kind,
                         "detail": d.detail, "note": d.note,
                         "predicted_gain_us":
                             round(d.predicted_gain_s * 1e6, 2)}
                        for d in self.refused],
        }


# ---------------------------------------------------------------------------
# exact-widening table for J003 cancellation
# ---------------------------------------------------------------------------
def _exactly_representable(a: str, b: str) -> bool:
    """True iff every value of dtype ``a`` survives a round-trip through
    dtype ``b`` bit-exactly — the precondition for cancelling
    ``a -> b -> a`` convert churn."""
    try:
        da, db = np_dtype(a), np_dtype(b)
    except (TypeError, AttributeError):
        return False
    if da == db:
        return True

    #: (mantissa bits incl. implicit lead, exponent bits) for the float
    #: types; ml_dtypes smalls register as numpy kind 'V', so classify
    #: by name
    fl = {"bfloat16": (8, 8), "float16": (11, 5), "float32": (24, 8),
          "float64": (53, 11)}

    def kind(d):
        if str(d) in fl:
            return "f"
        return d.kind

    ka, kb = kind(da), kind(db)

    def fbits(d):
        return fl[str(d)]

    if ka == "b":
        return True  # bool round-trips through any numeric type
    if ka in "iu" and kb in "iu":
        ia, ib = onp.iinfo(da), onp.iinfo(db)
        return ib.min <= ia.min and ia.max <= ib.max
    if ka in "iu" and kb == "f":
        bits = da.itemsize * 8 - (1 if ka == "i" else 0)
        return fbits(db)[0] >= bits
    if ka == "f" and kb == "f":
        ma, ea = fbits(da)
        mb, eb = fbits(db)
        return mb >= ma and eb >= ea
    return False


# ---------------------------------------------------------------------------
# planning
# ---------------------------------------------------------------------------
def _aval(var):
    return getattr(var, "aval", None)


def _tensor_bytes(aval) -> float:
    import math

    try:
        return float(math.prod(aval.shape) or 1) * np_dtype(
            str(aval.dtype)).itemsize
    except Exception:  # noqa: BLE001
        return 0.0


def _padded_bytes(aval, pad_axes: Dict[int, int]) -> float:
    import math

    shape = list(aval.shape)
    for ax, tile in pad_axes.items():
        shape[ax] = _pad_up(shape[ax], tile)
    try:
        return float(math.prod(shape) or 1) * np_dtype(
            str(aval.dtype)).itemsize
    except Exception:  # noqa: BLE001
        return 0.0


def _plan_dot(eqn, model: CostModel) -> Optional[RewriteDecision]:
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = _aval(eqn.invars[0]), _aval(eqn.invars[1])
    out = _aval(eqn.outvars[0])
    if lhs is None or rhs is None or out is None:
        return None
    lhs_free = [i for i in range(len(lhs.shape))
                if i not in lc and i not in lb]
    rhs_free = [i for i in range(len(rhs.shape))
                if i not in rc and i not in rb]
    # innermost dim of each class is the one the register tiling bites
    lhs_pads: Dict[int, int] = {}
    rhs_pads: Dict[int, int] = {}
    if lhs_free and _misaligned(lhs.shape[lhs_free[-1]], TILE_SUBLANE):
        lhs_pads[lhs_free[-1]] = TILE_SUBLANE
    if lc and _misaligned(lhs.shape[lc[-1]], TILE_LANE):
        lhs_pads[lc[-1]] = TILE_LANE
        rhs_pads[rc[-1]] = TILE_LANE       # contraction pads in lockstep
    if rhs_free and _misaligned(rhs.shape[rhs_free[-1]], TILE_LANE):
        rhs_pads[rhs_free[-1]] = TILE_LANE
    if not lhs_pads and not rhs_pads:
        return None
    # output axis order: batch, lhs free, rhs free — padded wherever a
    # free dim was padded (the contraction dims never reach the output)
    out_pads: Dict[int, int] = {}
    if lhs_free and lhs_free[-1] in lhs_pads:
        out_pads[len(lb) + len(lhs_free) - 1] = TILE_SUBLANE
    if rhs_free and rhs_free[-1] in rhs_pads:
        out_pads[len(out.shape) - 1] = TILE_LANE
    detail = (f"dot M{[lhs.shape[i] for i in lhs_free]}"
              f"K{[lhs.shape[i] for i in lc]}"
              f"N{[rhs.shape[i] for i in rhs_free]}")
    return _gate_pad(eqn, model, "pad_dot", detail,
                     {"lhs_pads": lhs_pads, "rhs_pads": rhs_pads,
                      "out_pads": out_pads,
                      "out_slice": bool(out_pads)},
                     lhs, rhs, out)


def _plan_conv(eqn, model: CostModel) -> Optional[RewriteDecision]:
    dn = eqn.params["dimension_numbers"]
    lhs, rhs = _aval(eqn.invars[0]), _aval(eqn.invars[1])
    out = _aval(eqn.outvars[0])
    if lhs is None or rhs is None or out is None:
        return None
    c_in = lhs.shape[dn.lhs_spec[1]]
    c_out = rhs.shape[dn.rhs_spec[0]]
    if int(eqn.params.get("feature_group_count", 1)) != 1 \
            or int(eqn.params.get("batch_group_count", 1)) != 1:
        # grouped/depthwise: zero-padding channels would re-partition
        # the group->channel map — not an equivalence-preserving pad
        if _misaligned(c_in, TILE_SUBLANE) \
                or _misaligned(c_out, TILE_LANE):
            return RewriteDecision(
                "J001", (), "pad_conv", f"conv C{c_in}->{c_out}",
                False, 0.0,
                note="grouped/depthwise conv: padding would change the "
                     "group->channel partition; baseline entry stays")
        return None
    lhs_pads: Dict[int, int] = {}
    rhs_pads: Dict[int, int] = {}
    out_slice = False
    if _misaligned(c_in, TILE_SUBLANE):
        lhs_pads[dn.lhs_spec[1]] = TILE_SUBLANE
        rhs_pads[dn.rhs_spec[1]] = TILE_SUBLANE
    out_pads: Dict[int, int] = {}
    if _misaligned(c_out, TILE_LANE):
        rhs_pads[dn.rhs_spec[0]] = TILE_LANE
        out_pads[dn.out_spec[1]] = TILE_LANE
        out_slice = True
    if not lhs_pads and not rhs_pads:
        return None
    return _gate_pad(eqn, model, "pad_conv", f"conv C{c_in}->{c_out}",
                     {"lhs_pads": lhs_pads, "rhs_pads": rhs_pads,
                      "out_pads": out_pads, "out_slice": out_slice},
                     lhs, rhs, out)


def _gate_pad(eqn, model: CostModel, kind: str, detail: str,
              payload: Dict[str, Any], lhs, rhs, out
              ) -> RewriteDecision:
    """The J001 cost gate. On a TPU target the padded-tile FLOPs are
    identical either way (the MXU executes full (8, 128) tiles
    regardless), and so — crucially — are the HBM bytes: XLA:TPU lays
    tensors out tile-padded in HBM, so a 16-feature tensor streams
    128-lane lines whether or not the program pads it explicitly. What
    misalignment costs is the **boundary tax**: masking/relayout work
    where a compact logical shape meets the padded physical one at
    every MXU op. An in-graph zero-pad makes the padding explicit and
    fusable (the pad folds into the producer, the slice into the
    consumer), retiring the tax at the price of a bounded residual for
    the copies that fail to fuse::

        gain = sum(padded_bytes of misaligned tensors) / bw     (tax)
        cost = 0.5 * sum(padded - compact bytes introduced) / bw

    A **CPU target always refuses**: XLA:CPU computes compact shapes —
    there is no tile relayout to save, and the padded program does
    genuinely more multiplies (the predicted loss the no-regression
    guard tests pin)."""
    from .cost_model import _conv_features, _dot_features

    feats = (_dot_features(eqn) if kind == "pad_dot"
             else _conv_features(eqn))
    bw = model.hbm_gbps * 1e9 * model.mem_eff
    lhs_pads = payload["lhs_pads"]
    rhs_pads = payload["rhs_pads"]
    if model.backend == "cpu":
        extra_flops = feats.flops_padded - feats.flops_raw
        loss = -extra_flops / (model.peak_tflops * 1e12
                               * model.compute_eff)
        return RewriteDecision("J001", (), kind, detail, False, loss,
                               note="cpu target: no tile relayout to "
                                    "save, padding adds real FLOPs",
                               payload=payload)
    tax = 0.0
    residual = 0.0
    for aval, pads in ((lhs, lhs_pads), (rhs, rhs_pads)):
        if pads:
            tax += _padded_bytes(aval, pads) / bw
            residual += (_padded_bytes(aval, pads)
                         - _tensor_bytes(aval)) / bw
    if payload.get("out_slice"):
        out_pads = payload.get("out_pads", {})
        tax += _padded_bytes(out, out_pads) / bw  # out boundary retired
        residual += (_padded_bytes(out, out_pads)
                     - _tensor_bytes(out)) / bw
    residual *= 0.5  # pad/slice mostly fuse; charge half the delta
    gain = tax - residual
    return RewriteDecision("J001", (), kind, detail, gain > 0, gain,
                           note="" if gain > 0 else
                           "predicted loss after fusion residual",
                           payload=payload)


def _plan_convert(eqn, produced_by, model: CostModel
                  ) -> Optional[RewriteDecision]:
    src = eqn.invars[0]
    out = _aval(eqn.outvars[0])
    src_aval = _aval(src)
    if out is None or src_aval is None:
        return None
    src_eqn = produced_by.get(id(src))
    if src_eqn is None \
            or src_eqn.primitive.name != "convert_element_type":
        return None
    origin_var = src_eqn.invars[0]
    origin = _aval(origin_var)
    if origin is None or origin.dtype != out.dtype:
        return None
    detail = (f"churn:{origin.dtype}->{src_aval.dtype}->{out.dtype}")
    same_weak = bool(getattr(origin, "weak_type", False)) == bool(
        getattr(out, "weak_type", False))
    exact = _exactly_representable(str(origin.dtype), str(src_aval.dtype))
    bw = model.hbm_gbps * 1e9 * model.mem_eff
    gain = (_tensor_bytes(src_aval) + _tensor_bytes(out)) \
        * model.fusion_discount / bw
    if not (exact and same_weak):
        return RewriteDecision(
            "J003", (), "cancel_convert", detail, False, gain,
            note="lossy round-trip: cancelling would change numerics "
                 "(hoist the precision boundary in the model instead)")
    return RewriteDecision("J003", (), "cancel_convert", detail, True,
                           gain, payload={"origin_id": id(origin_var)})


_INLINE_PRIMS = {"pjit", "closed_call", "core_call"}


def plan(closed, model: CostModel,
         rules: Sequence[str] = ("J001", "J003")
         ) -> List[RewriteDecision]:
    """Walk the jaxpr (inlining-eligible bodies included) and emit one
    decision per candidate equation, each gated by the cost model."""
    decisions: List[RewriteDecision] = []

    def walk(jx, path: Tuple[int, ...]):
        produced_by: Dict[int, Any] = {}
        for i, eqn in enumerate(jx.eqns):
            prim = eqn.primitive.name
            d = None
            if prim == "dot_general" and "J001" in rules:
                d = _plan_dot(eqn, model)
            elif prim == "conv_general_dilated" and "J001" in rules:
                d = _plan_conv(eqn, model)
            elif prim == "convert_element_type" and "J003" in rules:
                d = _plan_convert(eqn, produced_by, model)
            elif prim in _INLINE_PRIMS:
                sub = eqn.params.get("jaxpr")
                inner = getattr(sub, "jaxpr", sub)
                if inner is not None and hasattr(inner, "eqns"):
                    walk(inner, path + (i,))
            if d is not None:
                d.path = path + (i,)
                decisions.append(d)
            for ov in eqn.outvars:
                produced_by[id(ov)] = eqn
        return decisions

    jaxpr = getattr(closed, "jaxpr", closed)
    return walk(jaxpr, ())


# ---------------------------------------------------------------------------
# the re-interpreter
# ---------------------------------------------------------------------------
def _apply_pad_dot(eqn, invals, payload):
    from ...ops.nn import pad_to_tile, unpad_slice

    lhs, rhs = invals[0], invals[1]
    lhs = pad_to_tile(lhs, payload["lhs_pads"])
    rhs = pad_to_tile(rhs, payload["rhs_pads"])
    out = eqn.primitive.bind(lhs, rhs, **eqn.params)
    return [unpad_slice(out, _aval(eqn.outvars[0]).shape)]


def _apply_pad_conv(eqn, invals, payload):
    from ...ops.nn import pad_to_tile, unpad_slice

    lhs, rhs = invals[0], invals[1]
    lhs = pad_to_tile(lhs, payload["lhs_pads"])
    rhs = pad_to_tile(rhs, payload["rhs_pads"])
    out = eqn.primitive.bind(lhs, rhs, **eqn.params)
    return [unpad_slice(out, _aval(eqn.outvars[0]).shape)]


def eval_rewritten(closed, decisions: Sequence[RewriteDecision],
                   consts, *flat_args):
    """Replay a ClosedJaxpr through live jax ops with the planned
    (applied) decisions substituted. Returns flat outputs."""
    from jax.extend import core as jcore

    by_path = {d.path: d for d in decisions if d.applied}

    def run(jx, path: Tuple[int, ...], env: Dict[int, Any],
            jconsts, args):
        for v, val in zip(jx.constvars, jconsts):
            env[id(v)] = val
        for v, val in zip(jx.invars, args):
            env[id(v)] = val

        def read(v):
            if isinstance(v, jcore.Literal):
                return v.val
            return env[id(v)]

        for i, eqn in enumerate(jx.eqns):
            prim = eqn.primitive.name
            d = by_path.get(path + (i,))
            invals = [read(v) for v in eqn.invars]
            if d is not None and d.kind == "pad_dot":
                outs = _apply_pad_dot(eqn, invals, d.payload)
            elif d is not None and d.kind == "pad_conv":
                outs = _apply_pad_conv(eqn, invals, d.payload)
            elif d is not None and d.kind == "cancel_convert":
                # bit-exact: route the origin value straight through
                src_eqn_out = env.get(d.payload["origin_id"], None)
                if src_eqn_out is None:   # origin was a literal/const
                    outs = [eqn.primitive.bind(*invals, **eqn.params)]
                else:
                    outs = [src_eqn_out]
            elif prim in _INLINE_PRIMS and "jaxpr" in eqn.params:
                # inlining a nested jit body is semantically a no-op
                # under the enclosing trace, and it is where nested
                # rewrite decisions land
                sub = eqn.params["jaxpr"]
                inner = getattr(sub, "jaxpr", sub)
                sub_consts = list(getattr(sub, "consts", ()))
                outs = run(inner, path + (i,), env, sub_consts, invals)
            else:
                # the jax.core.eval_jaxpr idiom: get_bind_params turns
                # stored eqn params back into bindable form (callable
                # subfuns for custom_jvp/vjp_call, remat, …) — so
                # custom gradient rules survive the replay intact
                subfuns, bind_params = eqn.primitive.get_bind_params(
                    eqn.params)
                out = eqn.primitive.bind(*subfuns, *invals,
                                         **bind_params)
                outs = (out if eqn.primitive.multiple_results
                        else [out])
            for v, val in zip(eqn.outvars, outs):
                env[id(v)] = val
        return [read(v) for v in jx.outvars]

    jaxpr = getattr(closed, "jaxpr", closed)
    return run(jaxpr, (), {}, list(consts), list(flat_args))


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------
def rewrite_callable(fn: Callable, *example_args,
                     model: Optional[CostModel] = None,
                     rules: Sequence[str] = ("J001", "J003"),
                     mode_override: Optional[str] = None,
                     scope: str = "callable",
                     ) -> Tuple[Callable, RewriteReport]:
    """Plan + (mode permitting) apply rewrites over ``fn``.

    Returns ``(fn', report)``. Under ``MXNET_TPU_OPT=off`` nothing is
    even planned; under ``advise`` (the default) the report carries the
    plan but ``fn' is fn``; pass ``mode_override='rewrite'`` (or set the
    env) to transform. ``model`` defaults to the **live** backend's cost
    model — pass ``CostModel.for_backend('tpu', 'TPU v5 lite')`` to gate
    for a TPU deployment from a CPU process."""
    import jax

    md = mode(mode_override)
    model = model or CostModel.for_backend()
    report = RewriteReport(mode=md, backend=model.backend, scope=scope)
    if md == "off":
        return fn, report

    closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(
        *example_args)
    decisions = plan(closed, model, rules)
    applied_c, refused_c = _counters()
    for d in decisions:
        if d.applied and md == "rewrite":
            report.applied.append(d)
            report.predicted_gain_s += d.predicted_gain_s
            applied_c.labels(rule=d.rule).inc()
        else:
            if d.applied:           # advise mode: a would-apply
                d = RewriteDecision(d.rule, d.path, d.kind, d.detail,
                                    False, d.predicted_gain_s,
                                    note="advise mode (set MXNET_TPU_OPT"
                                         "=rewrite to apply)",
                                    payload=d.payload)
            elif md == "rewrite":
                # the refusal counter means "the gate said no", not
                # "the mode was advise" — only live-transform runs
                # count, so dashboards watching refusals see genuine
                # predicted-loss/exactness verdicts
                refused_c.labels(rule=d.rule).inc()
            report.refused.append(d)
    if not report.applied:
        return fn, report

    _, out_tree = jax.tree_util.tree_flatten(out_shape)
    ex_flat, in_tree = jax.tree_util.tree_flatten(example_args)
    ex_avals = [(tuple(getattr(a, "shape", ())),
                 str(getattr(a, "dtype", type(a).__name__)))
                for a in map(jax.api_util.shaped_abstractify, ex_flat)]
    live = [d for d in report.applied]

    def rewritten(*args):
        flat, tree = jax.tree_util.tree_flatten(args)
        if tree != in_tree:
            raise TypeError(
                f"rewritten callable expects the example structure "
                f"{in_tree}, got {tree}")
        # the replay (and its slice-back shapes) is SPECIALIZED to the
        # traced avals — a different batch size must be a loud error,
        # not rows silently sliced away
        for i, (leaf, (shape, dtype)) in enumerate(zip(flat, ex_avals)):
            aval = jax.api_util.shaped_abstractify(leaf)
            if (tuple(aval.shape), str(aval.dtype)) != (shape, dtype):
                raise TypeError(
                    f"rewritten callable is specialized to the example "
                    f"avals: leaf {i} expects {dtype}{list(shape)}, got "
                    f"{aval.dtype}{list(aval.shape)} — re-run "
                    "rewrite_callable with the new example")
        outs = eval_rewritten(closed, live, closed.consts, *flat)
        return jax.tree_util.tree_unflatten(out_tree, outs)

    rewritten.__name__ = getattr(fn, "__name__", "fn") + "_opt"
    rewritten.opt_report = report
    return rewritten, report


def rewrite_block(block, *example_inputs, training: bool = False,
                  model: Optional[CostModel] = None,
                  rules: Sequence[str] = ("J001", "J003"),
                  mode_override: Optional[str] = None,
                  scope: Optional[str] = None):
    """Rewrite a gluon (Hybrid)Block's pure forward.

    Returns ``(fn, params, report)`` where ``fn(params, *inputs)`` is
    the (possibly) transformed pure function — the same seam
    ``analysis.lint_block`` lints, so ``lint_callable(fn, params, *x)``
    on the result shows exactly which findings the rewrite retired."""
    import jax.numpy as jnp

    from ...ndarray.ndarray import ndarray as _nd, _unwrap, _wrap

    inputs = tuple(x if isinstance(x, _nd) else _wrap(jnp.asarray(x))
                   for x in example_inputs)
    if any(p._data is None for p in block.collect_params().values()):
        try:
            block.initialize()
        except Exception:  # noqa: BLE001 — already/deferred initialized
            pass
    scope = scope or type(block).__name__
    fn, params0 = block.functionalize(*inputs, training=training)

    def user_outputs(params, *ivals):
        out, _new_params = fn(params, *ivals)
        return out

    new_fn, report = rewrite_callable(
        user_outputs, params0, *[_unwrap(x) for x in inputs],
        model=model, rules=rules, mode_override=mode_override,
        scope=scope)
    return new_fn, params0, report


# ---------------------------------------------------------------------------
# the equivalence oracle
# ---------------------------------------------------------------------------
#: per-dtype relative tolerance for float comparisons: a tile pad only
#: changes the *order* zeros enter a reduction, so the bound is a few
#: ulps of the compute dtype, not a loose allclose
_FLOAT_RTOL = {"float64": 1e-12, "float32": 2e-5, "float16": 2e-2,
               "bfloat16": 2e-2}


def check_equivalence(ref_fn: Callable, new_fn: Callable, *args,
                      bitwise: Optional[bool] = None) -> Dict[str, Any]:
    """Interpret-mode oracle: run both callables op-by-op (no XLA
    fusion — ``jax.disable_jit``) on the same concrete inputs and
    compare every output leaf. Integer/bool leaves must match
    **bitwise**; float leaves within a few ulps of their dtype
    (``bitwise=True`` forces exact everywhere). Returns a dict with
    ``equal`` and per-leaf max errors; raises nothing — the caller
    decides whether a mismatch is fatal."""
    import jax

    with jax.disable_jit():
        ref = ref_fn(*args)
        out = new_fn(*args)
    ref_leaves = jax.tree_util.tree_leaves(ref)
    out_leaves = jax.tree_util.tree_leaves(out)
    result: Dict[str, Any] = {"equal": True, "leaves": [],
                              "n_leaves": len(ref_leaves)}
    if len(ref_leaves) != len(out_leaves):
        result["equal"] = False
        result["error"] = (f"leaf count {len(out_leaves)} != "
                           f"{len(ref_leaves)}")
        return result
    for i, (a, b) in enumerate(zip(ref_leaves, out_leaves)):
        a = onp.asarray(a)
        b = onp.asarray(b)
        row: Dict[str, Any] = {"leaf": i, "dtype": str(a.dtype),
                               "shape": list(a.shape)}
        if a.dtype != b.dtype or a.shape != b.shape:
            row["mismatch"] = f"aval {b.dtype}{b.shape}"
            result["equal"] = False
            result["leaves"].append(row)
            continue
        exact = bitwise if bitwise is not None else (
            a.dtype.kind not in "fc" and str(a.dtype) != "bfloat16")
        if exact or a.dtype.kind in "biu":
            ok = bool(onp.array_equal(a, b))
            row["bitwise"] = ok
        else:
            af = a.astype(onp.float64)
            bf = b.astype(onp.float64)
            denom = onp.maximum(onp.abs(af), 1.0)
            err = float(onp.max(onp.abs(af - bf) / denom)) \
                if af.size else 0.0
            tol = _FLOAT_RTOL.get(str(a.dtype), 1e-5)
            ok = err <= tol
            row["max_rel_err"] = err
            row["rtol"] = tol
        if not ok:
            result["equal"] = False
            row["mismatch"] = "value"
        result["leaves"].append(row)
    return result
