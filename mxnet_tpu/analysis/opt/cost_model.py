"""Analytic TPU cost model over jaxprs (the gating brain of ``opt``).

"A Learned Performance Model for TPUs" (arXiv:2008.01040) showed that
the features a TPU cost model needs are statically visible in the IR:
**padded-tile FLOPs** (what the MXU actually executes after (8, 128)
sublane/lane padding — not the algorithmic count), **bytes moved**
through HBM (dtype-aware), and **per-launch overhead**. This module
computes exactly those features from a jaxpr and folds them through a
per-op roofline::

    t(op)  = max(flops_padded / (peak * eff * rate(dtype)),
                 bytes / (bw * mem_eff))
    t(step) = sum_ops t(op) + launch_overhead / steps_per_launch

The constants (``compute_eff``, ``mem_eff``, ``fusion_discount``,
``launch_overhead_us``…) are **calibrated** against the banked TPU
corpus in ``benchmark/results_*.json`` (:mod:`.calibration`) — the repo
has been paying for that training data on every daemon capture — and
the fit is validated offline by rank correlation (:func:`spearman`)
between predicted and banked step times, no TPU required.

The model is deliberately analytic and inspectable: every estimate
carries a per-op breakdown (:class:`CostEstimate.top`) so a rewrite or
autotune decision can be justified in one printed line. It never
touches a backend — pure tracing + host arithmetic (tpulint A001-clean
by construction).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as onp

from ..jaxpr_rules import TILE_LANE, TILE_SUBLANE

__all__ = [
    "CostModel", "CostEstimate", "OpCost", "OpFeatures",
    "extract_features", "spearman",
]


def _pad_up(d: int, tile: int) -> int:
    return -(-int(d) // tile) * tile


def np_dtype(name) -> onp.dtype:
    """``numpy.dtype`` that also resolves the ml_dtypes smalls
    (``bfloat16`` & friends, which plain numpy refuses)."""
    try:
        return onp.dtype(name)
    except TypeError:
        import ml_dtypes

        return onp.dtype(getattr(ml_dtypes, str(name)))


#: matmul/conv rate multipliers vs the native one-pass bf16 MXU peak.
#: fp32 on the MXU is the bf16_3x emulation ("high", the bench default:
#: ~1/3 rate; "highest" is 6-pass); f64 is software-emulated; int8 runs
#: the int8 MXU path (banked micro: 1.157x bf16 on matmul).
_DTYPE_RATE = {
    "bfloat16": 1.0,
    "float16": 1.0,
    "float32": 1.0 / 3.0,
    "float64": 0.1,
    "int8": 1.157,
    "uint8": 1.157,
}


def _matmul_rate(dtype: str, fp32_rate: float) -> float:
    if dtype == "float32":
        return fp32_rate
    return _DTYPE_RATE.get(dtype, fp32_rate)


#: primitives whose operand/result bytes are charged in full — they
#: materialize real HBM traffic (matrix units, reductions, data
#: movement). Everything else is assumed fusable and charged at
#: ``fusion_discount`` of its naive bytes.
_MAJOR_PRIMS = {
    "dot_general", "conv_general_dilated", "gather", "scatter",
    "scatter-add", "dynamic_slice", "dynamic_update_slice",
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "argmax", "argmin", "sort", "cumsum", "transpose", "reduce_window",
    "select_and_scatter_add",
}


@dataclass(frozen=True)
class OpFeatures:
    """Constant-independent features of one equation — the calibration
    set stores arrays of these so refitting constants never re-traces."""
    prim: str
    flops_raw: float          # 2*MACs on algorithmic dims
    flops_padded: float       # 2*MACs on (8,128)-tile-padded dims
    bytes: float              # operand + result bytes, dtype-aware
    major: bool               # charged in full vs fusion-discounted
    dtype: str                # compute dtype (rate selection)
    detail: str = ""


@dataclass
class OpCost:
    features: OpFeatures
    t_compute_s: float
    t_memory_s: float

    @property
    def t_s(self) -> float:
        return max(self.t_compute_s, self.t_memory_s)

    @property
    def bound(self) -> str:
        return "compute" if self.t_compute_s >= self.t_memory_s \
            else "memory"

    def render(self) -> str:
        f = self.features
        return (f"{f.prim:22s} {f.detail:28s} {self.t_s * 1e3:8.3f} ms "
                f"[{self.bound}-bound, {f.flops_padded / 1e9:.2f} "
                f"padded GFLOP, {f.bytes / 1e6:.2f} MB, {f.dtype}]")


@dataclass
class CostEstimate:
    """One scored program: totals + the per-op breakdown that justifies
    every rewrite/tune decision built on it."""
    flops_raw: float = 0.0
    flops_padded: float = 0.0
    bytes_total: float = 0.0        # post-fusion-discount charged bytes
    bytes_naive: float = 0.0        # raw per-eqn operand+result bytes
    t_compute_s: float = 0.0        # sum of per-op compute terms
    t_memory_s: float = 0.0         # sum of per-op memory terms
    t_ops_s: float = 0.0            # sum of per-op rooflines
    t_launch_s: float = 0.0
    n_ops: int = 0
    ops: List[OpCost] = field(default_factory=list)

    @property
    def t_total_s(self) -> float:
        return self.t_ops_s + self.t_launch_s

    @property
    def tile_waste(self) -> float:
        """Fraction of padded-tile FLOPs that are padding (0 = perfectly
        tile-aligned) — the J001 aggregate for a whole program."""
        if not self.flops_padded:
            return 0.0
        return 1.0 - self.flops_raw / self.flops_padded

    def top(self, n: int = 5) -> List[OpCost]:
        return sorted(self.ops, key=lambda o: -o.t_s)[:n]

    def render(self, n: int = 5) -> str:
        lines = [
            f"predicted {self.t_total_s * 1e3:.3f} ms/launch "
            f"({self.t_ops_s * 1e3:.3f} ops + "
            f"{self.t_launch_s * 1e3:.3f} launch); "
            f"{self.flops_padded / 1e9:.2f} padded GFLOP "
            f"({100 * self.tile_waste:.0f}% tile waste), "
            f"{self.bytes_total / 1e6:.1f} MB charged HBM",
        ]
        lines += ["  " + o.render() for o in self.top(n)]
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# feature extraction (constant-free)
# ---------------------------------------------------------------------------
def _aval_bytes(var) -> float:
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0.0
    try:
        itemsize = np_dtype(str(dtype)).itemsize
    except (TypeError, AttributeError):
        itemsize = 4
    return float(math.prod(shape) or 1) * itemsize


def _dot_dims(eqn) -> Optional[Tuple[List[int], List[int], List[int],
                                     List[int]]]:
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs = getattr(eqn.invars[0], "aval", None)
    rhs = getattr(eqn.invars[1], "aval", None)
    if lhs is None or rhs is None:
        return None
    b = [lhs.shape[i] for i in lb]
    k = [lhs.shape[i] for i in lc]
    m = [d for i, d in enumerate(lhs.shape) if i not in lc and i not in lb]
    n = [d for i, d in enumerate(rhs.shape) if i not in rc and i not in rb]
    return b, m, k, n


def _dot_features(eqn) -> OpFeatures:
    dims = _dot_dims(eqn)
    if dims is None:
        return OpFeatures("dot_general", 0, 0, 0, True, "float32")
    b, m, k, n = dims
    raw = 2.0 * math.prod(b) * math.prod(m) * math.prod(k) * math.prod(n)
    # MXU tiling: M rides sublanes (8), K and N ride lanes (128). Pad
    # the innermost dim of each class (the one the tiling bites); outer
    # dims of the same class multiply through unpadded.
    pm = math.prod(m[:-1]) * _pad_up(m[-1], TILE_SUBLANE) if m else 1
    pk = math.prod(k[:-1]) * _pad_up(k[-1], TILE_LANE) if k else 1
    pn = math.prod(n[:-1]) * _pad_up(n[-1], TILE_LANE) if n else 1
    padded = 2.0 * math.prod(b) * pm * pk * pn
    dtype = str(eqn.invars[0].aval.dtype)
    detail = (f"M{math.prod(m)}K{math.prod(k)}N{math.prod(n)}"
              + (f"B{math.prod(b)}" if b else ""))
    bytes_ = sum(_aval_bytes(v) for v in eqn.invars) \
        + sum(_aval_bytes(v) for v in eqn.outvars)
    return OpFeatures("dot_general", raw, padded, bytes_, True, dtype,
                      detail)


def _conv_features(eqn) -> OpFeatures:
    dn = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    rhs = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    groups = int(eqn.params.get("feature_group_count", 1))
    c_in_g = rhs.shape[dn.rhs_spec[1]]       # in channels per group
    c_out = rhs.shape[dn.rhs_spec[0]]
    kernel_sp = math.prod(rhs.shape[d] for d in dn.rhs_spec[2:])
    out_sp = math.prod(out.shape[d] for d in dn.out_spec[2:])
    batch = out.shape[dn.out_spec[0]]
    raw = 2.0 * batch * out_sp * c_out * kernel_sp * c_in_g
    # conv as implicit matmul: M = batch*out_spatial (sublane), K =
    # C_in/g * kernel (C_in rides the sublane register tiling the J001
    # rule checks), N = C_out (lane)
    padded = (2.0 * _pad_up(batch * out_sp, TILE_SUBLANE)
              * _pad_up(c_in_g, TILE_SUBLANE) * kernel_sp
              * (groups * _pad_up(-(-c_out // groups), TILE_LANE)
                 if groups > 1 else _pad_up(c_out, TILE_LANE)))
    dtype = str(lhs.dtype)
    bytes_ = sum(_aval_bytes(v) for v in eqn.invars) \
        + sum(_aval_bytes(v) for v in eqn.outvars)
    return OpFeatures("conv_general_dilated", raw, padded, bytes_, True,
                      dtype, f"C{c_in_g * groups}->{c_out}x{kernel_sp}")


def _generic_features(eqn) -> OpFeatures:
    prim = eqn.primitive.name
    bytes_ = sum(_aval_bytes(v) for v in eqn.invars) \
        + sum(_aval_bytes(v) for v in eqn.outvars)
    dtype = "float32"
    for v in eqn.outvars:
        aval = getattr(v, "aval", None)
        if aval is not None and getattr(aval, "dtype", None) is not None:
            dtype = str(aval.dtype)
            break
    return OpFeatures(prim, 0.0, 0.0, bytes_, prim in _MAJOR_PRIMS, dtype)


def _sub_jaxprs_weighted(eqn):
    """Yield (sub_jaxpr, weight) under an eqn: scan bodies run ``length``
    times, cond branches are alternatives (the walk charges the heaviest
    via weight=-1 sentinel handled by caller), everything else once."""
    prim = eqn.primitive.name
    if prim == "scan":
        length = eqn.params.get("length", 1)
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", v)
            if hasattr(inner, "eqns"):
                yield inner, float(length)
        return
    for v in eqn.params.values():
        for item in (v if isinstance(v, (tuple, list)) else (v,)):
            inner = getattr(item, "jaxpr", item)
            if hasattr(inner, "eqns") and hasattr(inner, "outvars"):
                yield inner, 1.0


def extract_features(closed) -> List[Tuple[OpFeatures, float]]:
    """Walk a (Closed)Jaxpr recursively into ``(features, weight)``
    rows — the constant-free half of an estimate, cacheable per
    program (calibration refits constants against these without
    re-tracing)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    rows: List[Tuple[OpFeatures, float]] = []

    def walk(jx, weight: float):
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            if prim == "dot_general":
                rows.append((_dot_features(eqn), weight))
            elif prim == "conv_general_dilated":
                rows.append((_conv_features(eqn), weight))
            elif prim == "cond":
                # one branch executes — charge the heaviest by bytes
                subs = [b for b in eqn.params.get("branches", ())
                        if hasattr(getattr(b, "jaxpr", b), "eqns")]
                if subs:
                    best = max(subs, key=lambda b: sum(
                        _aval_bytes(v) for e in getattr(b, "jaxpr", b).eqns
                        for v in e.outvars))
                    walk(getattr(best, "jaxpr", best), weight)
                continue
            else:
                has_sub = False
                for sub, w in _sub_jaxprs_weighted(eqn):
                    has_sub = True
                    walk(sub, weight * w)
                if not has_sub:
                    rows.append((_generic_features(eqn), weight))
        return rows

    return walk(jaxpr, 1.0)


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------
@dataclass
class CostModel:
    """Analytic roofline with calibratable constants.

    Defaults are the v5e fit against the banked corpus (see
    ``benchmark/results_opt_cpu.json`` → ``calibration``); use
    :meth:`for_backend` to resolve peaks for the live (or a target)
    device, and :meth:`calibrate` to refit constants when the corpus
    grows.
    """
    peak_tflops: float = 197.0       # native-dtype MXU peak
    hbm_gbps: float = 542.8          # measured v5e (results_hbm_tpu.json)
    compute_eff: float = 0.45        # achievable fraction of peak
    mem_eff: float = 0.55
    launch_overhead_us: float = 4500.0   # per launch (axon tunnel ~4.5ms)
    fusion_discount: float = 0.08    # charged fraction of fusable bytes
    fp32_matmul_rate: float = 1.0 / 3.0  # "high" = bf16_3x
    backend: str = "tpu"
    device_kind: str = "TPU v5 lite"

    # -- construction -----------------------------------------------------
    @classmethod
    def for_backend(cls, backend: Optional[str] = None,
                    device_kind: Optional[str] = None) -> "CostModel":
        """Model for the live backend (or an explicit target: pass
        ``backend='tpu', device_kind='TPU v5 lite'`` to score TPU
        deployments from a CPU process — how the lint/rewrite gate runs
        in CI). TPU peaks resolve through :mod:`mxnet_tpu.telemetry.mfu`
        (measured HBM row when banked, spec otherwise)."""
        if backend is None:
            import jax

            from ...base import failsoft_call
            try:
                backend = failsoft_call(jax.default_backend)
                if device_kind is None:
                    devs = failsoft_call(jax.devices)
                    device_kind = getattr(devs[0], "device_kind", "")
            except Exception:  # noqa: BLE001 — backend down: score CPU
                backend = "cpu"
        device_kind = device_kind or ""
        if backend == "cpu":
            # XLA:CPU: no MXU, no tile padding, no tunnel. Peak ~ a few
            # vectorized cores; dispatch is a local call. fp32 runs full
            # rate (there is no bf16 unit to emulate against).
            return cls(peak_tflops=0.05, hbm_gbps=12.0, compute_eff=0.5,
                       mem_eff=0.5, launch_overhead_us=40.0,
                       fusion_discount=0.25, fp32_matmul_rate=1.0,
                       backend="cpu", device_kind=device_kind or "cpu")
        from ...telemetry import mfu

        peak = mfu.peak_bf16_tflops(device_kind) or cls.peak_tflops
        bw = mfu.bank().hbm_gbps(device_kind) or cls.hbm_gbps
        return cls(peak_tflops=peak, hbm_gbps=bw, backend=backend,
                   device_kind=device_kind or "tpu")

    # -- scoring ----------------------------------------------------------
    def op_cost(self, f: OpFeatures) -> OpCost:
        flops = f.flops_padded if self.backend == "tpu" else f.flops_raw
        rate = _matmul_rate(f.dtype, self.fp32_matmul_rate) \
            if flops else 1.0
        t_c = flops / (self.peak_tflops * 1e12 * self.compute_eff * rate) \
            if flops else 0.0
        charged = f.bytes * (1.0 if f.major else self.fusion_discount)
        t_m = charged / (self.hbm_gbps * 1e9 * self.mem_eff)
        return OpCost(f, t_c, t_m)

    def estimate_features(self, rows: Sequence[Tuple[OpFeatures, float]],
                          steps_per_launch: int = 1) -> CostEstimate:
        est = CostEstimate()
        for f, w in rows:
            oc = self.op_cost(f)
            est.flops_raw += w * f.flops_raw
            est.flops_padded += w * f.flops_padded
            est.bytes_naive += w * f.bytes
            est.bytes_total += w * f.bytes * (
                1.0 if f.major else self.fusion_discount)
            est.t_compute_s += w * oc.t_compute_s
            est.t_memory_s += w * oc.t_memory_s
            est.t_ops_s += w * oc.t_s
            est.n_ops += 1
            est.ops.append(oc)
        est.t_launch_s = self.launch_overhead_us * 1e-6 / max(
            1, int(steps_per_launch))
        return est

    def estimate_jaxpr(self, closed,
                       steps_per_launch: int = 1) -> CostEstimate:
        return self.estimate_features(extract_features(closed),
                                      steps_per_launch=steps_per_launch)

    def estimate_callable(self, fn, *args,
                          steps_per_launch: int = 1) -> CostEstimate:
        """Trace ``fn`` (no compile, no execute) and estimate it."""
        import jax

        closed = jax.make_jaxpr(fn)(*args)
        return self.estimate_jaxpr(closed,
                                   steps_per_launch=steps_per_launch)

    # -- calibration ------------------------------------------------------
    def calibrate(self, samples: Sequence[Tuple[
            Sequence[Tuple[OpFeatures, float]], int, float]],
            passes: int = 3) -> Tuple["CostModel", Dict[str, Any]]:
        """Refit constants against ``(feature_rows, steps_per_launch,
        observed_step_s)`` samples by deterministic coordinate descent
        over per-constant grids, minimizing mean squared log error
        (ranking-friendly: log-space symmetric). Returns the fitted
        model + a diagnostics dict (spearman/msle before and after)."""
        grids = {
            "compute_eff": [0.2, 0.3, 0.4, 0.45, 0.5, 0.6, 0.7, 0.8],
            "mem_eff": [0.3, 0.4, 0.5, 0.55, 0.6, 0.7, 0.8],
            "fusion_discount": [0.02, 0.05, 0.08, 0.12, 0.2, 0.3, 0.5],
            "launch_overhead_us": [50., 500., 1500., 3000., 4500., 6000.],
            "fp32_matmul_rate": [0.2, 1 / 3, 0.5, 1.0],
        }

        def msle(model: "CostModel") -> float:
            errs = []
            for rows, spl, obs in samples:
                pred = model.estimate_features(rows, spl).t_total_s
                errs.append(math.log(max(pred, 1e-9) / max(obs, 1e-9)) ** 2)
            return sum(errs) / max(1, len(errs))

        def rank(model: "CostModel") -> float:
            preds = [model.estimate_features(r, s).t_total_s
                     for r, s, _ in samples]
            return spearman(preds, [o for _, _, o in samples])

        before = {"msle": msle(self), "spearman": rank(self)}
        best = self
        best_err = before["msle"]
        for _ in range(passes):
            for name, grid in grids.items():
                for val in grid:
                    cand = replace(best, **{name: val})
                    err = msle(cand)
                    if err < best_err - 1e-12:
                        best, best_err = cand, err
        diag = {"before": before,
                "after": {"msle": best_err, "spearman": rank(best)},
                "n_samples": len(samples)}
        return best, diag


def spearman(xs: Sequence[float], ys: Sequence[float]) -> float:
    """Spearman rank correlation (average ranks on ties; no scipy)."""
    def ranks(vs):
        order = sorted(range(len(vs)), key=lambda i: vs[i])
        r = [0.0] * len(vs)
        i = 0
        while i < len(order):
            j = i
            while j + 1 < len(order) \
                    and vs[order[j + 1]] == vs[order[i]]:
                j += 1
            avg = (i + j) / 2.0
            for k in range(i, j + 1):
                r[order[k]] = avg
            i = j + 1
        return r

    if len(xs) != len(ys) or len(xs) < 2:
        return 0.0
    rx, ry = ranks(list(xs)), ranks(list(ys))
    mx = sum(rx) / len(rx)
    my = sum(ry) / len(ry)
    cov = sum((a - mx) * (b - my) for a, b in zip(rx, ry))
    vx = math.sqrt(sum((a - mx) ** 2 for a in rx))
    vy = math.sqrt(sum((b - my) ** 2 for b in ry))
    if not vx or not vy:
        return 0.0
    return cov / (vx * vy)
