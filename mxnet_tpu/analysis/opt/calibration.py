"""Calibrate the cost model against the banked TPU corpus.

Every daemon capture in ``benchmark/results_*.json`` is a *measured*
(workload, step time) pair on real hardware — free training data for
the analytic model (:mod:`.cost_model`), the observation TVM
(arXiv:1802.04799) and the learned TPU cost model (arXiv:2008.01040)
both build on. This module:

- harvests the banked rows that carry enough provenance to reconstruct
  the workload (model, precision, batch, steps_per_launch, throughput):
  the train/infer tables in ``results_train_tpu.json`` /
  ``results_infer_tpu.json`` plus the resnet headline rows,
- re-traces each workload's jaxpr **on CPU** (``jax.make_jaxpr`` only —
  no compile, no TPU needed) and extracts constant-free features,
- pairs them into calibration samples for
  :meth:`~.cost_model.CostModel.calibrate`, and scores rank fidelity
  (:func:`~.cost_model.spearman` of predicted vs banked step time).

The whole loop is offline and deterministic, so "is the cost model
still sane after this change" is a tier-1 test, not a TPU session.
"""
from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as onp

from .cost_model import CostModel, OpFeatures, extract_features, spearman

__all__ = ["CorpusRow", "banked_rows", "corpus", "calibrate_banked",
           "calibration_table"]


def _bank_dir() -> Optional[str]:
    env = os.environ.get("MXNET_TPU_ROOFLINE_DIR")
    if env:
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    cand = os.path.join(here, "benchmark")
    return cand if os.path.isdir(cand) else None


@dataclass
class CorpusRow:
    """One banked measurement with enough provenance to re-trace."""
    name: str                 # e.g. "resnet50_v1/bf16/infer/bs32"
    kind: str                 # "infer" | "train"
    model: str
    precision: str            # "fp32" | "bf16"
    batch: int
    steps_per_launch: int
    examples_per_s: float
    source: str
    device_kind: str = "TPU v5 lite"

    @property
    def observed_step_s(self) -> float:
        return self.batch / self.examples_per_s


def banked_rows(directory: Optional[str] = None) -> List[CorpusRow]:
    """Harvest reconstructable rows from the banked TPU corpus (rows
    without a throughput — e.g. failed captures — are skipped)."""
    directory = directory or _bank_dir()
    rows: List[CorpusRow] = []
    if not directory:
        return rows

    def load(name):
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    for fname, kind, key in (("results_infer_tpu.json", "infer",
                              "infer_img_s"),
                             ("results_train_tpu.json", "train",
                              "train_img_s")):
        doc = load(fname)
        if not doc:
            continue
        for r in doc.get("results", ()):
            val = r.get(key)
            model = r.get("model")
            if not (isinstance(val, (int, float)) and val > 0 and model):
                continue
            batch = int(r.get("batch", 32))
            rows.append(CorpusRow(
                name=f"{model}/{r.get('precision')}/{kind}/bs{batch}",
                kind=kind, model=model,
                precision=str(r.get("precision", "fp32")),
                batch=batch,
                steps_per_launch=int(r.get("steps_per_launch") or 16),
                examples_per_s=float(val), source=fname,
                device_kind=str(doc.get("device_kind",
                                        "TPU v5 lite"))))
    # de-dup by name keeping the first (files are curated best-of rows)
    seen, out = set(), []
    for r in rows:
        if r.name not in seen:
            seen.add(r.name)
            out.append(r)
    return out


# ---------------------------------------------------------------------------
# workload re-tracing (CPU, make_jaxpr only)
# ---------------------------------------------------------------------------
_feature_cache: Dict[Tuple, List[Tuple[OpFeatures, float]]] = {}


def _cast_params(params, dtype):
    import jax.numpy as jnp

    return {k: v.astype(dtype) if v.dtype == jnp.float32 else v
            for k, v in params.items()}


def _functionalized(model: str, batch: int):
    """(fn, params, x_np) for a zoo vision model. Deliberately NOT
    memoized: holding every zoo model's parameters at once (~1 GB for
    vgg16+resnet152 alone) would trade a few init seconds for OOM risk;
    the extracted features ARE memoized (:func:`features_for`)."""
    import mxnet_tpu as mx
    from ...gluon.model_zoo import vision

    from ... import initializer

    net = getattr(vision, model)(classes=1000)
    # Zero init: only shapes/dtypes reach the jaxpr, and drawing real
    # random weights is the dominant cost here (vgg16: ~50 s of PRNG
    # for 138M params vs ~3 s of tracing)
    net.initialize(init=initializer.Zero())
    size = 299 if "inception" in model else 224
    x_np = onp.zeros((batch, 3, size, size), dtype="float32")
    fn, params = net.functionalize(mx.np.array(x_np), training=False)
    return fn, params, x_np


def _trace_infer(model: str, batch: int, precision: str):
    import jax
    import jax.numpy as jnp

    fn, params, x_np = _functionalized(model, batch)
    dt = jnp.bfloat16 if precision == "bf16" else jnp.float32
    if dt != jnp.float32:
        params = _cast_params(params, dt)

    def fwd(p, x):
        out, _state = fn(p, x)
        return out

    return jax.make_jaxpr(fwd)(params, jnp.asarray(x_np, dt))


def _trace_train(model: str, batch: int, precision: str):
    """The train_bench step (fwd + bwd + SGD-momentum), traced not run:
    AMP pattern for bf16 (fp32 masters, bf16 compute)."""
    import jax
    import jax.numpy as jnp

    fn, params, x_np = _functionalized(model, batch)
    y_np = onp.zeros((batch,), dtype="int32")
    compute_dtype = jnp.bfloat16 if precision == "bf16" else jnp.float32
    velocity = {k: jnp.zeros_like(v) for k, v in params.items()
                if v.dtype == jnp.float32}

    def loss_fn(p, x, y):
        pc = _cast_params(p, compute_dtype) \
            if compute_dtype != jnp.float32 else p
        xc = x.astype(compute_dtype)
        out, state = fn(pc, xc)
        state = {k: s.astype(p[k].dtype) for k, s in state.items()}
        logp = jax.nn.log_softmax(out.astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()
        return nll, state

    def step(p, vel, x, y):
        (loss, state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, x, y)
        new_p, new_v = {}, {}
        for k, s in state.items():
            if k in vel:
                v = 0.9 * vel[k] + grads[k].astype(jnp.float32)
                new_v[k] = v
                new_p[k] = s - 0.05 * v
            else:
                new_p[k] = s
        return new_p, new_v, loss

    return jax.make_jaxpr(step)(params, velocity, jnp.asarray(x_np),
                                jnp.asarray(y_np))


def features_for(row: CorpusRow) -> List[Tuple[OpFeatures, float]]:
    """Constant-free cost features for one banked row (in-process
    memoized — refitting constants never re-traces)."""
    key = (row.model, row.batch, row.precision, row.kind)
    if key in _feature_cache:
        return _feature_cache[key]
    tracer = _trace_infer if row.kind == "infer" else _trace_train
    closed = tracer(row.model, row.batch, row.precision)
    rows = extract_features(closed)
    _feature_cache[key] = rows
    return rows


@dataclass
class CalSample:
    row: CorpusRow
    features: List[Tuple[OpFeatures, float]] = field(repr=False,
                                                     default_factory=list)

    def as_tuple(self):
        return (self.features, self.row.steps_per_launch,
                self.row.observed_step_s)


def corpus(kinds: Sequence[str] = ("infer", "train"),
           models: Optional[Sequence[str]] = None,
           max_rows: Optional[int] = None,
           directory: Optional[str] = None,
           log=None) -> List[CalSample]:
    """Build calibration samples: banked rows filtered by ``kinds`` /
    ``models``, each paired with its re-traced features. Rows whose
    workload cannot be rebuilt (zoo model missing) are skipped with a
    log line, never an error."""
    out: List[CalSample] = []
    for row in banked_rows(directory):
        if row.kind not in kinds:
            continue
        if models is not None and row.model not in models:
            continue
        try:
            feats = features_for(row)
        except Exception as e:  # noqa: BLE001 — a foreign row is not fatal
            if log:
                log(f"calibration: skipping {row.name}: {e!r}")
            continue
        out.append(CalSample(row, feats))
        if max_rows and len(out) >= max_rows:
            break
    return out


def calibrate_banked(model: Optional[CostModel] = None,
                     samples: Optional[List[CalSample]] = None,
                     **corpus_kw) -> Tuple[CostModel, Dict[str, Any]]:
    """End-to-end: harvest + trace + refit. Returns (fitted model,
    diagnostics incl. spearman before/after and the per-row table)."""
    model = model or CostModel()
    samples = samples if samples is not None else corpus(**corpus_kw)
    fitted, diag = model.calibrate([s.as_tuple() for s in samples])
    diag["table"] = calibration_table(fitted, samples)
    return fitted, diag


def calibration_table(model: CostModel,
                      samples: Sequence[CalSample]) -> List[Dict]:
    """Per-row predicted-vs-banked table (what ``opt_bench`` banks and
    the docs render)."""
    rows = []
    for s in samples:
        est = model.estimate_features(s.features,
                                      s.row.steps_per_launch)
        rows.append({
            "name": s.row.name,
            "source": s.row.source,
            "observed_step_ms": round(s.row.observed_step_s * 1e3, 3),
            "predicted_step_ms": round(est.t_total_s * 1e3, 3),
            "ratio": round(est.t_total_s / s.row.observed_step_s, 3),
            "padded_gflops": round(est.flops_padded / 1e9, 2),
            "tile_waste": round(est.tile_waste, 4),
            "charged_mb": round(est.bytes_total / 1e6, 2),
        })
    preds = [r["predicted_step_ms"] for r in rows]
    obs = [r["observed_step_ms"] for r in rows]
    rho = spearman(preds, obs) if len(rows) >= 2 else None
    for r in rows:
        r["spearman_all"] = round(rho, 4) if rho is not None else None
    return rows
