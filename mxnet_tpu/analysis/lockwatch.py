"""Runtime lock-order witness — the dynamic half of the C-rules.

:mod:`.concurrency` builds the *static* lock-order graph; this module
records the *observed* one. :func:`install` patches the
``threading.Lock`` / ``RLock`` / ``Condition`` factories so every lock
subsequently created **by mxnet_tpu code** (caller-frame filter — the
stdlib's own locks stay untouched) is wrapped in a thin proxy that
notes, per thread, which locks were already held at each acquisition.
Each (held → acquired) pair becomes an edge in a global order graph;
:func:`assert_acyclic` then proves no execution interleaving witnessed
an order inversion — the same property C001 checks statically, now
validated against real drills.

Lock identity is the *creation site* (``file:line``), so every replica's
``ReplicaPool._lock`` instance aggregates into one node, mirroring the
static analysis' structural naming.

Usage — armed opt-in inside tier-1 kill drills::

    from mxnet_tpu.analysis import lockwatch
    lockwatch.install()            # or MXNET_TPU_LOCKWATCH=1 + install_if_env()
    try:
        ...run the drill...
        lockwatch.assert_acyclic()
    finally:
        lockwatch.uninstall()

The proxy only observes: acquisition semantics (blocking, timeout,
``with``) pass straight through, and ``Condition.wait()``'s internal
release/re-acquire happens below the proxy — per-thread stacks stay
consistent because a waiting thread acquires nothing else meanwhile.
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

__all__ = [
    "install", "uninstall", "installed", "install_if_env", "reset",
    "edges", "cycles", "assert_acyclic", "report", "ENV_KNOB",
]

#: opt-in knob: set to 1/true to arm the witness via install_if_env().
ENV_KNOB = "MXNET_TPU_LOCKWATCH"

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_state_guard = threading.Lock()      # created before install(): raw lock
_tls = threading.local()

_installed = False
_orig: Dict[str, object] = {}
#: (held_site, acquired_site) -> observation count
_edges: Dict[Tuple[str, str], int] = {}
#: site -> number of proxied locks created there
_sites: Dict[str, int] = {}


def _caller_site() -> Optional[str]:
    """Creation site of the lock being constructed, or None when the
    caller is not mxnet_tpu code (stdlib, site-packages, tests)."""
    f = sys._getframe(2)
    fn = f.f_code.co_filename
    try:
        if os.path.commonpath([os.path.abspath(fn), _PKG_DIR]) != _PKG_DIR:
            return None
    except ValueError:
        return None
    rel = os.path.relpath(fn, os.path.dirname(_PKG_DIR))
    if rel.replace(os.sep, "/").startswith("mxnet_tpu/analysis/"):
        return None  # never watch the watcher
    return f"{rel.replace(os.sep, '/')}:{f.f_lineno}"


def _held_stack() -> List[str]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _note_acquire(site: str) -> None:
    stack = _held_stack()
    if stack:
        with _state_guard:
            for held in stack:
                if held != site:  # RLock re-entry is not an inversion
                    key = (held, site)
                    _edges[key] = _edges.get(key, 0) + 1
    stack.append(site)


def _note_release(site: str) -> None:
    stack = _held_stack()
    # locks may release out of LIFO order — drop the innermost match
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == site:
            del stack[i]
            break


class _LockProxy:
    """Order-recording wrapper over a Lock/RLock/Condition instance."""

    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site: str):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_site", site)

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            _note_acquire(self._site)
        return got

    def release(self, *args, **kwargs):
        self._inner.release(*args, **kwargs)
        _note_release(self._site)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        # wait/notify/locked/_is_owned/… delegate to the real object
        return getattr(object.__getattribute__(self, "_inner"), name)

    def __repr__(self):
        return f"<lockwatch {self._site} wrapping {self._inner!r}>"


def _wrap_factory(name: str):
    orig = _orig[name]

    def factory(*args, **kwargs):
        inner = orig(*args, **kwargs)
        site = _caller_site()
        if site is None:
            return inner
        with _state_guard:
            _sites[site] = _sites.get(site, 0) + 1
        return _LockProxy(inner, site)

    factory.__name__ = f"lockwatch_{name}"
    return factory


def install() -> None:
    """Patch the threading lock factories. Idempotent."""
    global _installed
    if _installed:
        return
    for name in ("Lock", "RLock", "Condition"):
        _orig[name] = getattr(threading, name)
    for name in ("Lock", "RLock", "Condition"):
        setattr(threading, name, _wrap_factory(name))
    _installed = True


def uninstall() -> None:
    """Restore the original factories (already-wrapped locks keep
    recording until they are garbage collected — harmless)."""
    global _installed
    if not _installed:
        return
    for name, orig in _orig.items():
        setattr(threading, name, orig)
    _orig.clear()
    _installed = False


def installed() -> bool:
    return _installed


def install_if_env(env: str = ENV_KNOB) -> bool:
    """Arm the witness when ``MXNET_TPU_LOCKWATCH`` is truthy — the
    opt-in path tier-1 drills use."""
    val = os.environ.get(env, "").strip().lower()
    if val in ("1", "true", "yes", "on"):
        install()
        return True
    return False


def reset() -> None:
    """Forget all observed edges and sites (keeps the patch armed)."""
    with _state_guard:
        _edges.clear()
        _sites.clear()


def edges() -> Dict[Tuple[str, str], int]:
    with _state_guard:
        return dict(_edges)


def cycles() -> List[List[str]]:
    """Elementary cycles in the observed order graph (canonical
    rotation, deduplicated) — each is a witnessed deadlock candidate."""
    graph: Dict[str, List[str]] = {}
    with _state_guard:
        for a, b in _edges:
            graph.setdefault(a, []).append(b)
    out: List[List[str]] = []
    seen = set()

    def canonical(path: List[str]) -> Tuple[str, ...]:
        i = path.index(min(path))
        return tuple(path[i:] + path[:i])

    def dfs(start: str, node: str, path: List[str], visited: set):
        for nxt in graph.get(node, ()):
            if nxt == start:
                key = canonical(path)
                if key not in seen:
                    seen.add(key)
                    out.append(list(key))
            elif nxt not in visited and len(path) < 8:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for node in sorted(graph):
        dfs(node, node, [node], {node})
    return out


def assert_acyclic() -> None:
    """Raise ``AssertionError`` when any lock-order cycle was observed."""
    cyc = cycles()
    if cyc:
        lines = [" -> ".join(c + [c[0]]) for c in cyc]
        raise AssertionError(
            "lockwatch observed lock-order cycle(s) — a real execution "
            "acquired these locks in inverted orders:\n  "
            + "\n  ".join(lines))


def report() -> dict:
    with _state_guard:
        rep = {
            "installed": _installed,
            "sites": dict(_sites),
            "edges": {f"{a} -> {b}": n for (a, b), n in _edges.items()},
        }
    rep["cycles"] = cycles()
    return rep
