"""jaxpr-level TPU anti-pattern rules (the IR half of tpulint).

Traces a callable / gluon block with ``jax.make_jaxpr`` and walks the
resulting IR — the same statically-visible features (operand padding
against the MXU tiles, dtype traffic, reduction shapes) a learned TPU
cost model consumes, surfaced as findings before anything runs.

Rules (catalog in :mod:`.findings`):

- **J001 tpu-dot-align** — ``dot_general``/``conv_general_dilated``
  operand dims that pad badly against the float32 (sublane=8, lane=128)
  register tiling. Flagged when the padded tile wastes ≥ 25% of its
  footprint (1000→1024 is fine at 2.3%; 130→256 is 49% waste and flags).
- **J002 tpu-f64-leak** — any float64 value inside the traced program.
  TPUs have no f64 ALU; XLA emulates it at >10× cost, and one weak-typed
  host scalar can upcast a whole subgraph.
- **J003 tpu-convert-churn** — a value converted to another dtype and
  straight back (``convert_element_type`` round-trip), the signature of
  mixed-precision boundaries drawn one op too narrow.
- **J004 tpu-scalar-reduce** — a full reduction to a rank-0 *program
  output*: the canonical host-sync magnet (``float(loss)`` right after).
- **J005 tpu-donation-miss** — an argument whose buffers are all
  reproduced in the outputs (an in-place update) but is absent from
  ``donate_argnums``: the step pays double HBM for every such buffer.
  Cross-checked against the live ``gluon.Trainer`` fused step via
  :func:`lint_trainer`.
"""
from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as onp

from .findings import Finding

TILE_SUBLANE = 8
TILE_LANE = 128
WASTE_THRESHOLD = 0.25

_REDUCE_PRIMS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "argmax", "argmin",
}
# shape/dtype plumbing a reduction result may flow through on its way to
# becoming a program output
_PASSTHROUGH_PRIMS = {
    "convert_element_type", "copy", "squeeze", "reshape", "stop_gradient",
    "device_put",
}


def _waste(dim: int, tile: int) -> float:
    padded = -(-dim // tile) * tile
    return (padded - dim) / padded


def _pad_note(dim: int, tile: int) -> str:
    padded = -(-dim // tile) * tile
    return f"{dim}->{padded} ({100 * _waste(dim, tile):.0f}% pad waste)"


def _misaligned(dim: int, tile: int) -> bool:
    return dim > 1 and _waste(dim, tile) >= WASTE_THRESHOLD


def _aval(var):
    return getattr(var, "aval", None)


def _sub_jaxprs(params: dict):
    """Yield nested (Closed)Jaxprs out of an eqn's params (pjit bodies,
    cond branches, scan/while carcasses, custom_vjp closures)."""
    for v in params.values():
        for item in (v if isinstance(v, (tuple, list)) else (v,)):
            inner = getattr(item, "jaxpr", item)
            if hasattr(inner, "eqns") and hasattr(inner, "outvars"):
                yield inner


def lint_jaxpr(closed, scope: str = "jaxpr") -> List[Finding]:
    """Walk a (Closed)Jaxpr recursively and emit J001–J004 findings."""
    jaxpr = getattr(closed, "jaxpr", closed)
    findings: List[Finding] = []
    seen: set = set()

    def emit(rule, message, detail, hint=""):
        if (rule, detail) in seen:
            return
        seen.add((rule, detail))
        findings.append(Finding(rule, message, scope=scope, detail=detail,
                                hint=hint))

    def check_f64(var, prim):
        aval = _aval(var)
        if aval is not None and str(getattr(aval, "dtype", "")) == "float64":
            emit("J002",
                 f"float64 value produced by `{prim}` — TPUs emulate f64 "
                 "in software",
                 f"{prim}:float64",
                 hint="keep the computation in float32/bfloat16; audit "
                      "host scalars and np.float64 inputs for weak-type "
                      "upcasts")

    def check_dot(eqn):
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs, rhs = _aval(eqn.invars[0]), _aval(eqn.invars[1])
        if lhs is None or rhs is None:
            return
        m = [d for i, d in enumerate(lhs.shape) if i not in lc and i not in lb]
        k = [lhs.shape[i] for i in lc]
        n = [d for i, d in enumerate(rhs.shape) if i not in rc and i not in rb]
        bad = ([("M", d, TILE_SUBLANE) for d in m if _misaligned(d, TILE_SUBLANE)]
               + [("K", d, TILE_LANE) for d in k if _misaligned(d, TILE_LANE)]
               + [("N", d, TILE_LANE) for d in n if _misaligned(d, TILE_LANE)])
        if bad:
            note = ", ".join(f"{ax}={_pad_note(d, t)}" for ax, d, t in bad)
            detail = "dot_general " + ",".join(
                f"{ax}{d}" for ax, d, _ in bad)
            emit("J001",
                 f"dot_general operands pad badly on the MXU: {note} "
                 f"(lhs{tuple(lhs.shape)} @ rhs{tuple(rhs.shape)})",
                 detail,
                 hint="round matmul dims to multiples of (8, 128) — pad "
                      "features/vocab once at model edges instead of "
                      "paying tile padding on every step")

    def check_conv(eqn):
        dn = eqn.params["dimension_numbers"]
        lhs, rhs = _aval(eqn.invars[0]), _aval(eqn.invars[1])
        if lhs is None or rhs is None:
            return
        c_in = lhs.shape[dn.lhs_spec[1]]
        c_out = rhs.shape[dn.rhs_spec[0]]
        bad = []
        if _misaligned(c_in, TILE_SUBLANE):
            bad.append(f"C_in={_pad_note(c_in, TILE_SUBLANE)}")
        if _misaligned(c_out, TILE_LANE):
            bad.append(f"C_out={_pad_note(c_out, TILE_LANE)}")
        if bad:
            emit("J001",
                 "conv feature dims pad badly on the MXU: "
                 + ", ".join(bad),
                 f"conv C{c_in}->{c_out}",
                 hint="prefer channel counts that are multiples of "
                      "(8, 128); for <=4-channel image stems enable the "
                      "space-to-depth rewrite (MXNET_TPU_STEM_S2D)")

    def walk(jx):
        produced_by: Dict[Any, Any] = {}
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            for ov in eqn.outvars:
                check_f64(ov, prim)
                produced_by[ov] = eqn
            if prim == "dot_general":
                check_dot(eqn)
            elif prim == "conv_general_dilated":
                check_conv(eqn)
            elif prim == "convert_element_type":
                src = eqn.invars[0]
                src_eqn = produced_by.get(src)
                if (src_eqn is not None
                        and src_eqn.primitive.name == "convert_element_type"):
                    origin = _aval(src_eqn.invars[0])
                    out = _aval(eqn.outvars[0])
                    if (origin is not None and out is not None
                            and origin.dtype == out.dtype):
                        emit("J003",
                             f"dtype round-trip {origin.dtype}->"
                             f"{_aval(src).dtype}->{out.dtype} "
                             "(convert_element_type churn)",
                             f"churn:{origin.dtype}->{_aval(src).dtype}",
                             hint="hoist the precision boundary so the "
                                  "value is converted once, or keep the "
                                  "intermediate op in the narrow dtype")
            for sub in _sub_jaxprs(eqn.params):
                walk(sub)
        return produced_by

    produced_by = walk(jaxpr)

    # J004: only reductions whose scalar ESCAPES the program are flagged —
    # an internal scalar (epsilon guard, norm denominator) is free.
    for ov in jaxpr.outvars:
        var, hops = ov, 0
        while hops < 8:
            eqn = produced_by.get(var)
            if eqn is None:
                break
            prim = eqn.primitive.name
            if prim in _PASSTHROUGH_PRIMS:
                var, hops = eqn.invars[0], hops + 1
                continue
            aval = _aval(ov)
            if (prim in _REDUCE_PRIMS and aval is not None
                    and tuple(getattr(aval, "shape", (1,))) == ()):
                findings.append(Finding(
                    "J004",
                    f"`{prim}` reduces to a scalar program output — the "
                    "caller will almost certainly sync it to host per step",
                    scope=scope, detail=f"scalar:{prim}",
                    hint="keep running statistics on device and fetch "
                         "once per epoch/log-interval, or batch scalars "
                         "into one array before transferring"))
            break
    return findings


def lint_callable(fn, *example_args, scope: str = "callable",
                  enable_x64: bool = False,
                  static_argnums: Sequence[int] = ()) -> List[Finding]:
    """Trace ``fn`` with ``jax.make_jaxpr`` and lint the IR."""
    import jax

    if enable_x64:
        with jax.experimental.enable_x64(True):
            closed = jax.make_jaxpr(
                fn, static_argnums=tuple(static_argnums))(*example_args)
    else:
        closed = jax.make_jaxpr(
            fn, static_argnums=tuple(static_argnums))(*example_args)
    return lint_jaxpr(closed, scope=scope)


def lint_block(block, *example_inputs, scope: Optional[str] = None,
               training: bool = False) -> List[Finding]:
    """Trace a gluon block (or exported SymbolBlock) and lint its jaxpr.

    ``example_inputs`` may be mx ndarrays, numpy arrays, or anything
    ``jnp.asarray`` accepts. Parameters are initialized on demand.
    """
    import jax
    import jax.numpy as jnp

    from ..ndarray.ndarray import ndarray as _nd, _unwrap, _wrap

    inputs = tuple(x if isinstance(x, _nd) else _wrap(jnp.asarray(x))
                   for x in example_inputs)
    if any(p._data is None for p in block.collect_params().values()):
        try:
            block.initialize()
        except Exception:  # noqa: BLE001 — already-initialized / deferred
            pass
    scope = scope or type(block).__name__

    if hasattr(block, "functionalize"):
        fn, params0 = block.functionalize(*inputs, training=training)

        def user_outputs(params, *ivals):
            out, _new_params = fn(params, *ivals)
            return out

        closed = jax.make_jaxpr(user_outputs)(
            params0, *[_unwrap(x) for x in inputs])
        return lint_jaxpr(closed, scope=scope)

    # plain Block (e.g. Sequential container): trace __call__ directly
    # with params baked as constants — every aval the rules care about
    # (operand dims, dtypes, reductions) is still in the IR
    from .. import numpy_extension as npx
    from ..numpy import random as _random

    def fwd(key, *ivals):
        wrapped = tuple(_wrap(v) for v in ivals)
        with npx.functional_mode(key, training):
            out = block(*wrapped)
        return jax.tree_util.tree_map(
            lambda v: v._data if isinstance(v, _nd) else v, out,
            is_leaf=lambda v: isinstance(v, _nd))

    # hybridized children draw from the thread-local global RNG inside
    # the trace, which would leave a tracer in _rng.key — restore it
    saved_key = _random._rng.key
    try:
        closed = jax.make_jaxpr(fwd)(
            jax.random.PRNGKey(0), *[_unwrap(x) for x in inputs])
    finally:
        _random._rng.key = saved_key
    return lint_jaxpr(closed, scope=scope)


def find_donation_misses(fn, example_args: Sequence[Any],
                         donate_argnums: Sequence[int] = (),
                         scope: str = "jit") -> List[Finding]:
    """J005: arguments whose buffers are all reproduced in the outputs
    (in-place updates in functional clothing) but are not donated.

    Matching is a greedy multiset walk over (shape, dtype) avals in
    argument order, so of weights/grads/states with identical shapes only
    the args that can still claim output buffers count as update-like —
    the XLA aliasing rule donation itself uses. Scalar-only args
    (lr, step counters) are skipped.
    """
    import jax

    donate = set(donate_argnums if isinstance(donate_argnums, (tuple, list,
                                                               set, frozenset))
                 else (donate_argnums,))
    out = jax.eval_shape(fn, *example_args)
    pool = Counter((tuple(l.shape), str(l.dtype))
                   for l in jax.tree_util.tree_leaves(out))
    findings: List[Finding] = []
    # donated args claim their output slots FIRST (declared intent), so a
    # shape-twin like grads can't steal the states' slots and fire a
    # false J005 on the real Trainer step
    order = sorted(range(len(example_args)),
                   key=lambda i: (i not in donate, i))
    for i in order:
        arg = example_args[i]
        leaves = jax.tree_util.tree_leaves(arg)
        avals = [(tuple(l.shape), str(l.dtype)) for l in leaves]
        if not avals or all(int(onp.prod(s)) <= 1 for s, _ in avals):
            continue
        need = Counter(avals)
        if any(pool[k] < n for k, n in need.items()):
            continue  # not update-like: outputs don't cover this arg
        pool.subtract(need)
        if i not in donate:
            nbytes = sum(
                int(onp.prod(s)) * onp.dtype(d).itemsize for s, d in avals)
            findings.append(Finding(
                "J005",
                f"argument {i} is fully reproduced in the outputs "
                f"(~{nbytes / 1e6:.2f} MB of update-in-place buffers) but "
                "is not donated",
                scope=scope, detail=f"arg{i}",
                hint=f"pass donate_argnums=({i},) (plus the other updated "
                     "args) to jax.jit so XLA aliases the buffers instead "
                     "of double-allocating"))
    return findings


def lint_trainer(trainer, scope: str = "gluon.Trainer._build_jit_step"
                 ) -> List[Finding]:
    """Cross-check the live Trainer fused-update donation contract.

    Rebuilds the exact pure function + donate tuple the Trainer jits
    (``Trainer._fused_update_fn``) and runs :func:`find_donation_misses`
    over it with the real parameter/state avals.
    """
    idxs = [i for i, p in enumerate(trainer._params)
            if p.grad_req != "null" and p._data is not None]
    if not idxs or not getattr(trainer, "_jit_safe", True):
        return []
    if not trainer._states_ready:
        trainer._init_states()
    fused, donate = trainer._fused_update_fn(idxs)
    # the aval construction lives on the Trainer (also the prewarm()
    # path) so the linted signature can never drift from the jitted one
    args = trainer._fused_update_avals(idxs)
    return find_donation_misses(fused, args, donate, scope=scope)
