"""Python-source TPU anti-pattern rules (the AST half of tpulint).

Hot-path model: device->host syncs are only findings where they repeat
per step — inside ``hybrid_forward``/``forward`` methods (they also break
jit tracing outright), metric/optimizer ``update`` methods, and training
loops (any loop whose body calls ``.step(``/``.backward(`` or opens
``autograd.record()``). A sync in ``get()``/``__init__``/a script prologue
is free and never flagged.

Rules:

- **A001 tpu-host-sync-hot** — ``.asnumpy()``, ``.item()``,
  ``np/onp/numpy.asarray|array(...)``, ``float()/int()/bool()`` over a
  computed value, or iterating a tensor argument, inside a hot path.
- **A002 tpu-cache-key-hazard** — an ``MXNET_*`` env knob read inside
  traced code (``forward``/``hybrid_forward``, or a private lowering
  helper in an ``ops/`` module) whose name appears in **no** jit cache
  key. Cache keys are discovered, not declared: every function named
  ``*cache_key*`` or ``_signature`` contributes its ``MXNET_*`` string
  literals (``ops/nn.py:stem_s2d_cache_key`` and
  ``gluon/block.py:_signature`` today). The bug class this catches was
  fixed by hand once already (stem-s2d knob absent from the hybridize
  key, PR 1).
- **A003 tpu-f64-source** — ``float64`` dtype literals in ``gluon``/
  ``ops`` modules (low severity; host-side bookkeeping in f64 is often
  deliberate — suppress inline where it is).

Suppression: ``# tpulint: disable=A001`` (comma-separated ids or
``all``) on the finding's line or the line above banks an *intentional*
occurrence at the source, with the rule id in the code for reviewers.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding

HOT_METHODS = {"hybrid_forward", "forward", "update"}
SYNC_ATTRS = {"asnumpy", "item", "asscalar"}
NP_MODULE_NAMES = {"np", "onp", "numpy"}
NP_SYNC_FUNCS = {"asarray", "array"}
CAST_BUILTINS = {"float", "int", "bool"}
LOOP_HOT_CALLS = {"step", "backward", "record"}

_DISABLE_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\s]+|all)")
_ENV_KNOB_RE = re.compile(r"^MXNET_")


_METADATA_ATTRS = {"shape", "ndim", "size", "itemsize", "dtype"}


_HOST_FUNC_MODULES = NP_MODULE_NAMES | {"math"}


def _is_metadata_expr(node: ast.AST) -> bool:
    """True when the expression reads ONLY array *metadata* (shape math is
    static and free — ``int(onp.prod(x.shape[1:]))`` is not a sync).

    Every attribute access must be a metadata attr or a host-module
    function (``onp.prod``/``math.prod``); one device access anywhere —
    ``float(loss.sum() / batch.shape[0])`` — disqualifies the whole
    expression, so mixing in ``.shape`` cannot launder a sync."""
    saw_metadata = False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            if sub.attr in _METADATA_ATTRS:
                saw_metadata = True
            elif not (isinstance(sub.value, ast.Name)
                      and sub.value.id in _HOST_FUNC_MODULES):
                return False
    return saw_metadata


def _unparse(node, limit: int = 48) -> str:
    try:
        txt = ast.unparse(node)
    except Exception:  # noqa: BLE001
        txt = "<expr>"
    return txt if len(txt) <= limit else txt[: limit - 1] + "…"


def _suppressions(source: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _DISABLE_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out[lineno] = rules
    return out


def _suppressed(supp: Dict[int, Set[str]], rule: str, line: int) -> bool:
    for ln in (line, line - 1):
        rules = supp.get(ln)
        if rules and ("all" in rules or rule in rules):
            return True
    return False


def cache_key_knobs(source: str) -> Set[str]:
    """All ``MXNET_*`` string literals inside cache-key functions
    (``*cache_key*`` in the name, or ``_signature``)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return set()
    return _knobs_from_tree(tree)


def _knobs_from_tree(tree: ast.AST) -> Set[str]:
    knobs: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
                "cache_key" in node.name or node.name == "_signature"):
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str)
                        and _ENV_KNOB_RE.match(sub.value)):
                    knobs.add(sub.value)
    return knobs


def _is_env_read(node: ast.Call) -> Optional[str]:
    """Return the knob name when ``node`` is os.environ.get/os.getenv/
    environ.get with a literal MXNET_* first argument."""
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute):
        if fn.attr in ("get", "getenv"):
            base = fn.value
            if isinstance(base, ast.Attribute) and base.attr == "environ":
                name = "env"
            elif isinstance(base, ast.Name) and base.id in ("os", "environ"):
                name = "env"
        elif fn.attr == "environ":
            return None
    if name is None:
        return None
    if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
            node.args[0].value, str):
        knob = node.args[0].value
        if _ENV_KNOB_RE.match(knob):
            return knob
    return None


def _is_env_subscript(node: ast.Subscript) -> Optional[str]:
    """Return the knob name when ``node`` is ``os.environ["MXNET_*"]`` /
    ``environ["MXNET_*"]`` with a literal key."""
    base = node.value
    is_environ = (isinstance(base, ast.Attribute) and base.attr == "environ"
                  ) or (isinstance(base, ast.Name) and base.id == "environ")
    if not is_environ:
        return None
    key = node.slice
    if isinstance(key, ast.Constant) and isinstance(key.value, str) \
            and _ENV_KNOB_RE.match(key.value):
        return key.value
    return None


class _FileLinter(ast.NodeVisitor):
    def __init__(self, relpath: str, source: str, cache_keys: Set[str]):
        self.relpath = relpath
        self.supp = _suppressions(source)
        self.cache_keys = cache_keys
        self.findings: List[Finding] = []
        self.class_stack: List[str] = []
        self.func_stack: List[ast.AST] = []
        # (scope name, hot?, trace-path?, tensor params) per function
        self.ctx_stack: List[dict] = []
        self.loop_depth_hot = 0
        self.in_ops_module = "/ops/" in relpath.replace(os.sep, "/") or \
            relpath.replace(os.sep, "/").startswith("ops/")

    # -- helpers -----------------------------------------------------------
    def _scope(self) -> str:
        parts = list(self.class_stack)
        if self.ctx_stack:
            parts.append(self.ctx_stack[-1]["name"])
        return ".".join(parts) or "<module>"

    def _emit(self, rule: str, node: ast.AST, message: str, detail: str,
              hint: str = ""):
        line = getattr(node, "lineno", 0)
        if _suppressed(self.supp, rule, line):
            return
        self.findings.append(Finding(
            rule, message, path=self.relpath, line=line,
            scope=self._scope(), detail=detail, hint=hint))

    def _hot(self) -> bool:
        if self.loop_depth_hot > 0:
            return True
        return bool(self.ctx_stack and self.ctx_stack[-1]["hot"])

    def _trace_path(self) -> bool:
        return bool(self.ctx_stack and self.ctx_stack[-1]["trace"])

    # -- scope bookkeeping -------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef):
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node):
        in_class = bool(self.class_stack)
        hot = in_class and node.name in HOT_METHODS
        trace = node.name in ("forward", "hybrid_forward") or (
            self.in_ops_module and node.name.startswith("_")
            and "cache_key" not in node.name)
        if "cache_key" in node.name or node.name == "_signature":
            trace = False
        tensor_params: Set[str] = set()
        if node.name in ("forward", "hybrid_forward"):
            argnames = [a.arg for a in node.args.args]
            tensor_params = {a for a in argnames[1:] if a != "F"}
        self.ctx_stack.append(
            {"name": node.name, "hot": hot, "trace": trace,
             "tensors": tensor_params})
        # a def nested in a hot loop executes nothing per iteration — its
        # body is not hot-loop code (it gets its own hotness from ctx)
        saved_loop_depth, self.loop_depth_hot = self.loop_depth_hot, 0
        self.generic_visit(node)
        self.loop_depth_hot = saved_loop_depth
        self.ctx_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- training loops ----------------------------------------------------
    def _loop_is_hot(self, node) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                fn = sub.func
                if isinstance(fn, ast.Attribute) and fn.attr in LOOP_HOT_CALLS:
                    return True
        return False

    def _visit_loop(self, node):
        hot = self._loop_is_hot(node)
        # tensor-argument iteration inside forward (A001): `for row in x`
        if (self.ctx_stack and self.ctx_stack[-1]["tensors"]
                and isinstance(node, ast.For)
                and isinstance(node.iter, ast.Name)
                and node.iter.id in self.ctx_stack[-1]["tensors"]):
            self._emit(
                "A001", node,
                f"iterating tensor argument `{node.iter.id}` in "
                f"{self.ctx_stack[-1]['name']} syncs once per element and "
                "breaks jit tracing",
                detail=f"iter:{node.iter.id}",
                hint="vectorize with jnp ops / lax.scan instead of a "
                     "Python loop over rows")
        if hot:
            self.loop_depth_hot += 1
        self.generic_visit(node)
        if hot:
            self.loop_depth_hot -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    # -- the sync / knob detectors -----------------------------------------
    def visit_Call(self, node: ast.Call):
        fn = node.func
        if self._hot():
            if isinstance(fn, ast.Attribute) and fn.attr in SYNC_ATTRS:
                self._emit(
                    "A001", node,
                    f"`.{fn.attr}()` forces a device->host transfer in a "
                    "hot path",
                    detail=f"{fn.attr}:{_unparse(fn.value)}",
                    hint="accumulate on device and fetch once per "
                         "log-interval (one fused transfer per update)")
            elif (isinstance(fn, ast.Attribute)
                  and fn.attr in NP_SYNC_FUNCS
                  and isinstance(fn.value, ast.Name)
                  and fn.value.id in NP_MODULE_NAMES):
                self._emit(
                    "A001", node,
                    f"`{fn.value.id}.{fn.attr}(...)` materializes a device "
                    "array on host in a hot path",
                    detail=f"{fn.value.id}.{fn.attr}:{_unparse(node.args[0]) if node.args else ''}",
                    hint="keep the value in jnp; convert once at the "
                         "epoch/log boundary")
            elif (isinstance(fn, ast.Name) and fn.id in CAST_BUILTINS
                  and len(node.args) == 1
                  and isinstance(node.args[0], (ast.Call, ast.BinOp))
                  and not _is_metadata_expr(node.args[0])):
                self._emit(
                    "A001", node,
                    f"`{fn.id}({_unparse(node.args[0])})` blocks on the "
                    "device and syncs a scalar in a hot path",
                    detail=f"{fn.id}:{_unparse(node.args[0])}",
                    hint="defer scalarization: log from a device "
                         "accumulator at interval boundaries")
        self._check_knob(_is_env_read(node), node)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        self._check_knob(_is_env_subscript(node), node)
        self.generic_visit(node)

    def _check_knob(self, knob: Optional[str], node: ast.AST):
        if knob is not None and self._trace_path():
            if knob not in self.cache_keys:
                self._emit(
                    "A002", node,
                    f"env knob `{knob}` is read under trace but appears in "
                    "no jit cache key: flipping it serves stale executables",
                    detail=f"knob:{knob}",
                    hint="add the knob to the hybridize cache key (see "
                         "ops/nn.py:stem_s2d_cache_key wired into "
                         "gluon/block.py:_signature) or read it outside "
                         "traced code")

    def visit_Constant(self, node: ast.Constant):
        if (node.value == "float64"
                and any(seg in self.relpath.replace(os.sep, "/")
                        for seg in ("gluon/", "ops/"))):
            self._emit(
                "A003", node,
                "float64 dtype literal in accelerator-adjacent source",
                detail=f"f64:{self._scope()}",
                hint="use float32/bfloat16 for device values; if this is "
                     "deliberate host bookkeeping, suppress with "
                     "`# tpulint: disable=A003`")


def lint_source(source: str, relpath: str = "<string>",
                extra_cache_keys: Iterable[str] = ()) -> List[Finding]:
    """Lint one source text. Cache-key knobs are discovered from the same
    text plus ``extra_cache_keys`` (the cross-file set)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [_syntax_finding(e, relpath)]
    keys = _knobs_from_tree(tree) | set(extra_cache_keys)
    return _lint_tree(tree, source, relpath, keys)


def _syntax_finding(e: SyntaxError, relpath: str) -> Finding:
    return Finding("A000", f"syntax error: {e}", path=relpath,
                   line=e.lineno or 0, severity="high",
                   detail="syntax-error")


def _lint_tree(tree: ast.AST, source: str, relpath: str,
               cache_keys: Set[str]) -> List[Finding]:
    linter = _FileLinter(relpath, source, cache_keys)
    linter.visit(tree)
    return linter.findings


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path) and path.endswith(".py"):
            yield path
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_paths(paths: Sequence[str], root: Optional[str] = None
               ) -> List[Finding]:
    """Two-pass lint over files/directories: first collect every cache-key
    knob in the corpus, then lint each file against the union — a knob
    keyed in ``ops/nn.py`` must cover a read in ``gluon/``."""
    root = root or os.getcwd()
    # parse each file exactly once: knob collection and the lint walk
    # share the tree
    parsed: List[Tuple[str, str, object]] = []  # (rel, text, tree|SyntaxError)
    all_keys: Set[str] = set()
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root)
        try:
            tree = ast.parse(text)
        except SyntaxError as e:
            parsed.append((rel, text, e))
            continue
        parsed.append((rel, text, tree))
        all_keys |= _knobs_from_tree(tree)
    findings: List[Finding] = []
    for rel, text, tree in parsed:
        if isinstance(tree, SyntaxError):
            findings.append(_syntax_finding(tree, rel))
        else:
            findings.extend(_lint_tree(tree, text, rel, all_keys))
    return findings
