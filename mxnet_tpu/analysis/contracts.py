"""Contract-drift rules (the R* half of tpulint) — source lint plus a
code↔docs cross-check over the resilience and observability contracts.

The docs are load-bearing here: ``docs/resilience.md`` is the chaos-site
catalog operators arm campaigns from, ``docs/env_var.md`` is the knob
contract, ``docs/observability.md`` is the metric catalog dashboards and
SLO rules are written against. Eight PRs of cluster growth added sites,
knobs and series by hand in both places — these rules make the two
halves provably agree.

- **R001 tpu-swallowed-except** — a bare / ``except Exception`` handler
  in a retry/collective path whose body neither re-raises nor calls
  anything (no logging, no counter, no cleanup) — a fault silently
  eaten where the typed-taxonomy retry loops need to see it.
- **R002 tpu-untyped-raise** — ``raise RuntimeError/Exception`` in a
  module bound to the typed taxonomy (it imports ``TransientError`` /
  ``FatalError`` from ``base``). Operational faults must be typed so
  retry classifiers and drills can route them; ``ValueError`` /
  ``TypeError`` stay exempt (API misuse is the caller's bug by
  contract).
- **R003 tpu-contract-drift** — three-way drift gates, each direction a
  distinct finding:

  - chaos sites instrumented via ``chaos.site("…")`` / declared in
    ``chaos.SITES`` vs the ``docs/resilience.md`` site table;
  - ``MXNET_TPU_*`` env vars read in code (``os.environ`` or the
    ``base.env_*`` helpers, literal names) vs ``docs/env_var.md`` rows;
  - telemetry series registered with literal names
    (``registry.counter/gauge/histogram`` and ``profiler.Counter``,
    dot-sanitized) vs the ``docs/observability.md`` metric catalog
    (tables whose first header cell is ``Series``).

  Dynamically-named series (``f"aot.{name}"``) are statically
  invisible; their doc rows are banked in the baseline with a recorded
  justification instead of being deleted.

Suppression: the shared ``# tpulint: disable=R001`` grammar from
:mod:`.ast_rules` applies to R001/R002 (R003 findings live between
files — bank them in the baseline instead).
"""
from __future__ import annotations

import ast
import fnmatch
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .ast_rules import _suppressions, _suppressed, _unparse, iter_py_files
from .findings import Finding

#: modules whose except-handlers are retry/collective paths (R001).
R001_PATH_PREFIXES = (
    "mxnet_tpu/resilience/", "mxnet_tpu/parallel/", "mxnet_tpu/kvstore/",
    "mxnet_tpu/io/", "mxnet_tpu/serving/", "mxnet_tpu/checkpoint.py",
)

#: untyped builtins whose raise is an operational fault (R002). API
#: misuse types (ValueError/TypeError/KeyError/NotImplementedError)
#: are exempt by the fleet contract: client/config errors propagate.
R002_UNTYPED = {"RuntimeError", "Exception", "BaseException"}

#: scopes where best-effort swallowing is the teardown contract: a
#: close/reaper path must make progress past a half-dead peer, so an
#: empty ``except Exception: pass`` there is by design, not drift.
_TEARDOWN_RE = re.compile(
    r"^_*(safe_)?(close|shutdown|stop|abort|teardown|cancel|drain|"
    r"reset|clear|del|exit)(_|$)")

_ENV_RE = re.compile(r"^MXNET_TPU_[A-Z0-9_]+$")
_ENV_HELPER_RE = re.compile(r"^_?env_[a-z]+$")
_NAME_TOKEN_RE = re.compile(r"^[a-z][a-z0-9_.]*\*?$")
_DOC_TOKEN_RE = re.compile(r"`([^`]+)`")


# ---------------------------------------------------------------------------
# code inventory
# ---------------------------------------------------------------------------

class CodeInventory:
    def __init__(self):
        # name -> (relpath, line) of the first occurrence
        self.env_reads: Dict[str, Tuple[str, int]] = {}
        self.sites: Dict[str, Tuple[str, int]] = {}
        self.metrics: Dict[str, Tuple[str, int]] = {}


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _scan_file(rel: str, tree: ast.AST, inv: CodeInventory) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            arg0 = _const_str(node.args[0]) if node.args else None
            # env reads: os.environ.get / os.getenv / environ.setdefault
            # and the base.env_* typed helpers
            is_env_call = False
            if isinstance(fn, ast.Attribute) and fn.attr in (
                    "get", "getenv", "setdefault"):
                base = fn.value
                if (isinstance(base, ast.Attribute)
                        and base.attr == "environ") or (
                        isinstance(base, ast.Name)
                        and base.id in ("os", "environ")):
                    is_env_call = True
            elif isinstance(fn, ast.Name) and _ENV_HELPER_RE.match(fn.id):
                is_env_call = True
            elif isinstance(fn, ast.Attribute) and _ENV_HELPER_RE.match(
                    fn.attr):
                is_env_call = True
            if is_env_call and arg0 and _ENV_RE.match(arg0):
                inv.env_reads.setdefault(arg0, (rel, node.lineno))
            # chaos sites: chaos.site("…") / site("…")
            if ((isinstance(fn, ast.Attribute) and fn.attr == "site")
                    or (isinstance(fn, ast.Name) and fn.id == "site")):
                if arg0:
                    inv.sites.setdefault(arg0, (rel, node.lineno))
            # metric series: registry counter/gauge/histogram literals
            if isinstance(fn, ast.Attribute) and fn.attr in (
                    "counter", "gauge", "histogram") and arg0:
                inv.metrics.setdefault(arg0, (rel, node.lineno))
            # profiler.Counter(name="a.b") — re-registered as a gauge
            # with dots sanitized to underscores
            if ((isinstance(fn, ast.Attribute) and fn.attr == "Counter")
                    or (isinstance(fn, ast.Name) and fn.id == "Counter")):
                for kw in node.keywords:
                    if kw.arg == "name":
                        name = _const_str(kw.value)
                        if name:
                            inv.metrics.setdefault(
                                name.replace(".", "_"),
                                (rel, node.lineno))
        # a knob bound to an UPPERCASE constant (read indirectly, e.g.
        # lockwatch.ENV_KNOB) still names a live env-var contract
        if isinstance(node, ast.Assign):
            name = _const_str(node.value)
            if name and _ENV_RE.match(name):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id.isupper():
                        inv.env_reads.setdefault(name, (rel, node.lineno))
        # the declared SITES tuple in resilience/chaos.py
        if (isinstance(node, ast.Assign) and rel.replace(os.sep, "/")
                .endswith("resilience/chaos.py")):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "SITES" and \
                        isinstance(node.value, ast.Tuple):
                    for elt in node.value.elts:
                        name = _const_str(elt)
                        if name:
                            inv.sites.setdefault(name, (rel, elt.lineno))


def scan_code(paths: Sequence[str], root: str) -> CodeInventory:
    inv = CodeInventory()
    scan_paths = list(paths)
    # tools/ and benchmark/ participate in the env-var contract (bench
    # knobs are documented too) but tests do not — a test-only var is
    # not a product contract
    for extra in ("tools", "benchmark"):
        d = os.path.join(root, extra)
        if os.path.isdir(d):
            scan_paths.append(d)
    for path in iter_py_files(scan_paths):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue
        _scan_file(os.path.relpath(path, root), tree, inv)
    return inv


# ---------------------------------------------------------------------------
# doc-table parsing
# ---------------------------------------------------------------------------

def _doc_rows(text: str) -> List[Tuple[str, str, int]]:
    """Yield ``(header_first_cell, row_first_cell, lineno)`` for every
    data row of every pipe table in a markdown text."""
    rows: List[Tuple[str, str, int]] = []
    lines = text.splitlines()
    header: Optional[str] = None
    for i, line in enumerate(lines):
        if not line.lstrip().startswith("|"):
            header = None
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not cells:
            continue
        if all(re.fullmatch(r":?-{2,}:?", c) for c in cells if c):
            continue  # the |---| separator
        nxt = lines[i + 1].strip() if i + 1 < len(lines) else ""
        if nxt.startswith("|") and re.fullmatch(
                r"\|?[\s:|-]+\|?", nxt) and "-" in nxt:
            header = cells[0]
            continue
        if header is not None:
            rows.append((header, cells[0], i + 1))
    return rows


def _tokens(cell: str) -> List[str]:
    return _DOC_TOKEN_RE.findall(cell)


def doc_env_vars(text: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for header, cell, line in _doc_rows(text):
        if header.lower() != "variable":
            continue
        for tok in _tokens(cell):
            if _ENV_RE.match(tok) or (tok.startswith("MXNET_TPU_")
                                      and tok.endswith("*")):
                out.setdefault(tok, line)
    return out


def doc_sites(text: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for header, cell, line in _doc_rows(text):
        if header.lower() != "site":
            continue
        for tok in _tokens(cell):
            if _NAME_TOKEN_RE.match(tok):
                out.setdefault(tok, line)
    return out


def doc_metrics(text: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for header, cell, line in _doc_rows(text):
        if header.lower() != "series":
            continue
        for tok in _tokens(cell):
            tok = tok.split("{", 1)[0].strip()
            if tok and _NAME_TOKEN_RE.match(tok):
                out.setdefault(tok, line)
    return out


def _read_doc(docs_dir: str, name: str) -> Tuple[str, str]:
    path = os.path.join(docs_dir, name)
    try:
        with open(path, encoding="utf-8") as f:
            return f.read(), path
    except OSError:
        return "", path


# ---------------------------------------------------------------------------
# R003: the three drift gates
# ---------------------------------------------------------------------------

def _covered(name: str, documented: Dict[str, int]) -> bool:
    if name in documented:
        return True
    return any(fnmatch.fnmatchcase(name, pat)
               for pat in documented if pat.endswith("*"))


def _emitted(name: str, emitted: Dict[str, Tuple[str, int]]) -> bool:
    if name.endswith("*"):
        return any(fnmatch.fnmatchcase(e, name) for e in emitted)
    return name in emitted


def lint_drift(inv: CodeInventory, docs_dir: str,
               root: str) -> List[Finding]:
    findings: List[Finding] = []

    def drift(kind: str, code: Dict[str, Tuple[str, int]],
              documented: Dict[str, int], doc_rel: str,
              undoc_hint: str, stale_hint: str):
        for name, (rel, line) in sorted(code.items()):
            if not _covered(name, documented):
                findings.append(Finding(
                    "R003",
                    f"{kind} `{name}` exists in code but has no "
                    f"{doc_rel} row",
                    path=rel, line=line, scope=f"drift:{kind}",
                    detail=f"{kind}-undoc:{name}", hint=undoc_hint))
        for name, line in sorted(documented.items()):
            if name.endswith("*"):
                continue
            if not _emitted(name, code):
                findings.append(Finding(
                    "R003",
                    f"{doc_rel} documents {kind} `{name}` but nothing "
                    "in code produces it",
                    path=doc_rel, line=line, scope=f"drift:{kind}",
                    detail=f"{kind}-stale:{name}", hint=stale_hint))

    env_text, _ = _read_doc(docs_dir, "env_var.md")
    drift("env-var", inv.env_reads, doc_env_vars(env_text),
          "docs/env_var.md",
          "add a row to the docs/env_var.md knob table (Variable / "
          "Default / Effect)",
          "the knob is gone or renamed — delete the row, or bank with "
          "a justification if it is read dynamically")

    res_text, _ = _read_doc(docs_dir, "resilience.md")
    drift("chaos-site", inv.sites, doc_sites(res_text),
          "docs/resilience.md",
          "add a row to the docs/resilience.md chaos-site table "
          "(Site / Location) describing what each action simulates",
          "no chaos.site() call or SITES entry carries this name — "
          "delete the row or re-instrument the site")

    obs_text, _ = _read_doc(docs_dir, "observability.md")
    drift("metric", inv.metrics, doc_metrics(obs_text),
          "docs/observability.md",
          "add the series to the docs/observability.md metric catalog "
          "(Series / Kind / Source)",
          "no literal registration produces this series — delete the "
          "row, or bank with a justification when the name is built "
          "dynamically (f-string counter families)")

    return findings


# ---------------------------------------------------------------------------
# R001 / R002
# ---------------------------------------------------------------------------

class _ContractLinter(ast.NodeVisitor):
    def __init__(self, relpath: str, source: str, taxonomy_bound: bool):
        self.relpath = relpath
        self.supp = _suppressions(source)
        self.taxonomy_bound = taxonomy_bound
        self.findings: List[Finding] = []
        self.scope_stack: List[str] = []

    def _scope(self) -> str:
        return ".".join(self.scope_stack) or "<module>"

    def _emit(self, rule: str, node: ast.AST, message: str, detail: str,
              hint: str):
        line = getattr(node, "lineno", 0)
        if _suppressed(self.supp, rule, line):
            return
        self.findings.append(Finding(
            rule, message, path=self.relpath, line=line,
            scope=self._scope(), detail=detail, hint=hint))

    def _push(self, node):
        self.scope_stack.append(node.name)
        self.generic_visit(node)
        self.scope_stack.pop()

    visit_ClassDef = _push
    visit_FunctionDef = _push
    visit_AsyncFunctionDef = _push

    # R001 ------------------------------------------------------------------
    @staticmethod
    def _overbroad(handler: ast.ExceptHandler) -> Optional[str]:
        if handler.type is None:
            return "bare except"
        if isinstance(handler.type, ast.Name) and handler.type.id in (
                "Exception", "BaseException"):
            return f"except {handler.type.id}"
        return None

    @staticmethod
    def _swallows(handler: ast.ExceptHandler) -> bool:
        """True when the handler body has no raise and calls nothing —
        the fault vanishes without a log line, a counter, or cleanup."""
        for sub in ast.walk(handler):
            if isinstance(sub, (ast.Raise, ast.Call)):
                return False
        return True

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        in_scope = any(
            self.relpath.replace(os.sep, "/").startswith(pfx)
            for pfx in R001_PATH_PREFIXES)
        teardown = any(_TEARDOWN_RE.match(part)
                       for part in self.scope_stack)
        kind = self._overbroad(node)
        if in_scope and not teardown and kind and self._swallows(node):
            self._emit(
                "R001", node,
                f"{kind} swallows the fault silently in a "
                "retry/collective path",
                detail=f"swallow:{self._scope()}",
                hint="re-raise, classify into the typed taxonomy "
                     "(TransientError/FatalError), or at least log/count "
                     "the fault so drills can see it")
        self.generic_visit(node)

    # R002 ------------------------------------------------------------------
    def visit_Raise(self, node: ast.Raise):
        if self.taxonomy_bound and node.exc is not None:
            target = node.exc
            if isinstance(target, ast.Call):
                target = target.func
            if isinstance(target, ast.Name) and target.id in R002_UNTYPED:
                self._emit(
                    "R002", node,
                    f"raise of untyped `{target.id}` in a module bound "
                    "to the typed error taxonomy",
                    detail=f"untyped:{target.id}:{self._scope()}",
                    hint="raise TransientError (retryable) or FatalError "
                         "(not) from mxnet_tpu.base so retry loops and "
                         "drills can classify it")
        self.generic_visit(node)


def _taxonomy_bound(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            names = {a.name for a in node.names}
            if names & {"TransientError", "FatalError"}:
                return True
    return False


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               docs_dir: Optional[str] = None) -> List[Finding]:
    """Run R001/R002 over files and R003 against the docs contract
    tables (``docs_dir`` defaults to ``<root>/docs``; pass ``""`` to
    skip the drift gates)."""
    root = root or os.getcwd()
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root)
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue  # ast_rules reports A000
        linter = _ContractLinter(rel, text, _taxonomy_bound(tree))
        linter.visit(tree)
        findings.extend(linter.findings)
    if docs_dir is None:
        docs_dir = os.path.join(root, "docs")
    if docs_dir and os.path.isdir(docs_dir):
        inv = scan_code(paths, root)
        findings.extend(lint_drift(inv, docs_dir, root))
    return findings


__all__ = [
    "lint_paths", "scan_code", "lint_drift", "CodeInventory",
    "doc_env_vars", "doc_sites", "doc_metrics",
]
