"""Runtime retrace / host-sync sentinel (``MXNET_TPU_LINT``).

The static passes see what *would* fall off the fast path; this sentinel
watches what actually does, in-process, with near-zero overhead when off:

- **retraces** — every jit-cache miss in ``HybridBlock._call_cached``
  (the observer global ``gluon.block._retrace_observer``). A block that
  keeps tracing new signatures is a retrace storm: shapes that never
  stabilize, or a knob read under trace that is missing from the cache
  key (rule A002's runtime twin).
- **transfers** — every ``ndarray.asnumpy()`` (which ``item()``,
  ``float()``, ``int()``, ``bool()`` and ``__array__`` all funnel
  through; observer global ``ndarray.ndarray._transfer_observer``).

Counts are mirrored into ``mx.profiler`` counters
(``tpulint_retraces`` / ``tpulint_transfers``) so they land on the same
chrome-trace timeline as the ops that caused them.

Activation::

    MXNET_TPU_LINT=warn                      # budgets: retrace=8/block
    MXNET_TPU_LINT=raise:retrace=2,transfer=100
    MXNET_TPU_LINT=count                     # count only, never complain

or programmatically ``sentinel.activate(mode="warn", retrace_budget=2)``.
Past a budget the sentinel warns (:class:`TpuLintWarning`) or raises
(:class:`LintBudgetExceeded`); ``report()`` returns the tallies either
way.
"""
from __future__ import annotations

import os
import threading
import warnings
from typing import Dict, Optional

__all__ = [
    "TpuLintWarning", "LintBudgetExceeded", "activate", "activate_from_env",
    "deactivate", "active", "report", "reset_counts",
    "DEFAULT_RETRACE_BUDGET",
]

DEFAULT_RETRACE_BUDGET = 8

_lock = threading.Lock()
_state: Optional[dict] = None


class TpuLintWarning(UserWarning):
    """A tpulint runtime budget was exceeded (warn mode)."""


class LintBudgetExceeded(RuntimeError):
    """A tpulint runtime budget was exceeded (raise mode)."""


def _parse_env(value: str):
    """``mode[:k=v,k=v]`` -> (mode, retrace_budget, transfer_budget)."""
    mode, _, tail = value.partition(":")
    mode = (mode or "warn").strip().lower()
    if mode not in ("warn", "raise", "count"):
        warnings.warn(
            f"MXNET_TPU_LINT={value!r}: unknown mode {mode!r}, using "
            "'warn'", stacklevel=3)
        mode = "warn"
    retrace, transfer = DEFAULT_RETRACE_BUDGET, None
    for frag in filter(None, (f.strip() for f in tail.split(","))):
        key, _, val = frag.partition("=")
        try:
            num = int(val)
        except ValueError:
            warnings.warn(
                f"MXNET_TPU_LINT={value!r}: unparseable budget {frag!r} "
                "ignored", stacklevel=3)
            continue
        if key.strip() in ("retrace", "retraces"):
            retrace = num
        elif key.strip() in ("transfer", "transfers"):
            transfer = num
        else:
            warnings.warn(
                f"MXNET_TPU_LINT={value!r}: unknown budget key {key!r} "
                "ignored", stacklevel=3)
    return mode, retrace, transfer


def activate(mode: str = "warn",
             retrace_budget: int = DEFAULT_RETRACE_BUDGET,
             transfer_budget: Optional[int] = None) -> None:
    """Install the observers and start counting."""
    global _state
    import importlib

    from .. import profiler

    # explicit module resolution: `from ..ndarray import ndarray` yields
    # the CLASS (star-import shadows the submodule name)
    block_mod = importlib.import_module("mxnet_tpu.gluon.block")
    ndarray_mod = importlib.import_module("mxnet_tpu.ndarray.ndarray")

    with _lock:
        _state = {
            "mode": mode,
            "retrace_budget": retrace_budget,
            "transfer_budget": transfer_budget,
            "retraces": {},           # "<Block>@<id>" -> count
            "total_retraces": 0,
            "transfers": 0,
            "transfer_bytes": 0,
            "transfer_warned": False,
            "retrace_counter": profiler.Counter(name="tpulint_retraces"),
            "transfer_counter": profiler.Counter(name="tpulint_transfers"),
        }
    block_mod._retrace_observer = _on_retrace
    ndarray_mod._transfer_observer = _on_transfer


def activate_from_env() -> bool:
    value = os.environ.get("MXNET_TPU_LINT")
    if not value:
        return False
    mode, retrace, transfer = _parse_env(value)
    activate(mode=mode, retrace_budget=retrace, transfer_budget=transfer)
    return True


def deactivate() -> None:
    global _state
    import importlib

    block_mod = importlib.import_module("mxnet_tpu.gluon.block")
    ndarray_mod = importlib.import_module("mxnet_tpu.ndarray.ndarray")

    block_mod._retrace_observer = None
    ndarray_mod._transfer_observer = None
    with _lock:
        _state = None


def active() -> bool:
    return _state is not None


def reset_counts() -> None:
    with _lock:
        st = _state
        if st is None:
            return
        st["retraces"] = {}
        st["total_retraces"] = 0
        st["transfers"] = 0
        st["transfer_bytes"] = 0
        st["transfer_warned"] = False


def report() -> Dict:
    with _lock:
        st = _state
        if st is None:
            return {"active": False}
        return {
            "active": True,
            "mode": st["mode"],
            "retrace_budget": st["retrace_budget"],
            "transfer_budget": st["transfer_budget"],
            "retraces": dict(st["retraces"]),
            "total_retraces": st["total_retraces"],
            "transfers": st["transfers"],
            "transfer_bytes": st["transfer_bytes"],
        }


def _complain(st: dict, message: str) -> None:
    if st["mode"] == "raise":
        raise LintBudgetExceeded(message)
    if st["mode"] == "warn":
        warnings.warn(message, TpuLintWarning, stacklevel=4)


def _on_retrace(block, sig) -> None:
    st = _state
    if st is None:
        return
    key = f"{type(block).__name__}@{id(block):x}"
    with _lock:
        count = st["retraces"].get(key, 0) + 1
        st["retraces"][key] = count
        st["total_retraces"] += 1
    st["retrace_counter"].increment()
    budget = st["retrace_budget"]
    if budget is not None and count > budget:
        _complain(
            st,
            f"tpulint: {type(block).__name__} has traced {count} distinct "
            f"signatures (budget {budget}) — retrace storm: unstable input "
            "shapes/dtypes, or a knob flipping under trace (see "
            "docs/static_analysis.md, rule A002)")


def _on_transfer(arr) -> None:
    st = _state
    if st is None:
        return
    try:
        nbytes = int(arr.size) * arr.dtype.itemsize
    except Exception:  # noqa: BLE001 — abstract/tracer values carry no bytes
        nbytes = 0
    with _lock:
        st["transfers"] += 1
        st["transfer_bytes"] += nbytes
        count = st["transfers"]
        first_over = (st["transfer_budget"] is not None
                      and count > st["transfer_budget"]
                      and not st["transfer_warned"])
        if first_over:
            st["transfer_warned"] = True
    st["transfer_counter"].increment()
    if (st["transfer_budget"] is not None and count > st["transfer_budget"]
            and (first_over or st["mode"] == "raise")):
        _complain(
            st,
            f"tpulint: {count} device->host transfers "
            f"({st['transfer_bytes'] / 1e6:.2f} MB) exceed the budget of "
            f"{st['transfer_budget']} — hidden syncs on the hot path (see "
            "docs/static_analysis.md, rule A001)")
