"""The universal array value type, backed by ``jax.Array``.

Re-design of the reference NDArray (``include/mxnet/ndarray.h:82``,
``src/ndarray/``): a ref-counted device buffer plus an engine variable that
serializes readers/writers and an autograd entry. On TPU the XLA runtime
already provides async dispatch and buffer lifetime management, so this
class keeps the *contract* — ``wait_to_read``/``wait_to_write`` block until
pending async work (and surface async exceptions, the
``threaded_engine.cc:422`` behavior), ``ctx``/``copyto`` move data between
devices, in-place ops serialize — while the mechanism is jax.

Mutation model: jax arrays are immutable, so in-place ops rebind the
underlying buffer (functional update via ``.at[].set``). A version counter
detects stale autograd references, mirroring the reference's var
versioning (``threaded_engine.h:104 VersionedVarBlock``).
"""
from __future__ import annotations

import operator
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import (MXNetError, dtype_from_any, bfloat16, failsoft_call,
                    safe_devices)
from ..context import Context, current_context
from ..ops.dispatch import apply_op, autograd_state, is_recording

__all__ = ["ndarray", "NDArray", "array", "_wrap", "_unwrap"]

# tpulint runtime sentinel seam (analysis.sentinel): called with the
# ndarray on every device->host transfer. item()/float()/int()/bool()/
# __array__ all funnel through asnumpy, so one tap covers every implicit
# sync; a module-global None-check is the entire cost when off.
_transfer_observer = None


def _unwrap(x: Any):
    if isinstance(x, ndarray):
        return x._data
    return x


def _wrap(val) -> "ndarray":
    out = ndarray.__new__(ndarray)
    out._data = val
    out._grad = None
    out._grad_req = "null"
    out._fresh_grad_node = None
    out._version = 0
    return out


class ndarray:
    """Dense n-dimensional array on a device (reference NDArray / mx.np.ndarray)."""

    __slots__ = (
        "_data",
        "_grad",
        "_grad_req",
        "_fresh_grad_node",
        "_version",
        "__weakref__",
    )

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        if isinstance(data, ndarray):
            data = data._data
        dt = dtype_from_any(dtype) if dtype is not None else None
        if not isinstance(data, (jax.Array,)):
            data = onp.asarray(data, dtype=dt)
            # mx.np default-dtype semantics: float64 host data becomes
            # float32 unless the caller asked for float64 explicitly
            if dt is None and data.dtype == onp.float64:
                data = data.astype(onp.float32)
        # failsoft: array creation can be the process's first backend
        # touch — fall back to CPU instead of raising raw init errors
        val = failsoft_call(jnp.asarray, data, dtype=dt)
        if ctx is not None:
            val = jax.device_put(val, ctx.jax_device)
        self._data = val
        self._grad: Optional[ndarray] = None
        self._grad_req = "null"
        self._fresh_grad_node = None
        self._version = 0

    # ------------------------------------------------------------------
    # metadata
    # ------------------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return onp.dtype(self._data.dtype)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def size(self) -> int:
        return int(onp.prod(self.shape)) if self.shape else 1

    @property
    def ctx(self) -> Context:
        try:
            dev = self._data.devices().pop()
        except Exception:  # tracer inside jit — context is abstract
            return current_context()
        if dev.platform == "cpu":
            cpu_devs = [d for d in safe_devices() if d.platform == "cpu"]
            try:
                idx = cpu_devs.index(dev)
            except ValueError:
                idx = 0
            # on the virtual-device CPU test rig, cpu devices double as tpus
            if all(d.platform == "cpu" for d in safe_devices()):
                return Context("tpu", idx) if idx else Context("cpu", 0)
            return Context("cpu", idx)
        accel = [d for d in safe_devices() if d.platform != "cpu"]
        return Context("tpu", accel.index(dev))

    context = ctx
    device = ctx

    @property
    def T(self) -> "ndarray":
        return self.transpose()

    @property
    def real(self) -> "ndarray":
        from ..ops.dispatch import apply_op

        return apply_op(lambda v: v.real, [self], name="real")

    @property
    def imag(self) -> "ndarray":
        from ..ops.dispatch import apply_op

        return apply_op(lambda v: v.imag, [self], name="imag")

    def conj(self) -> "ndarray":
        from ..ops.dispatch import apply_op

        return apply_op(lambda v: v.conj(), [self], name="conj")

    conjugate = conj

    @property
    def grad(self) -> Optional["ndarray"]:
        return self._grad

    # ------------------------------------------------------------------
    # engine contract: async wait + exception surfacing
    # ------------------------------------------------------------------
    def wait_to_read(self) -> None:
        """Block until async work producing this array completes; raises any
        deferred exception (reference ndarray.h:374 + threaded_engine.cc:422)."""
        try:
            self._data.block_until_ready()
        except AttributeError:
            pass  # tracer
        except Exception:
            # error observed here → clear from the engine's pending set so
            # waitall() does not rethrow it (reference clears the var's
            # exception_ptr once thrown)
            from .. import engine as _engine

            _engine.observed(self._data)
            raise

    def wait_to_write(self) -> None:
        self.wait_to_read()

    # ------------------------------------------------------------------
    # host transfer / conversion
    # ------------------------------------------------------------------
    def asnumpy(self) -> onp.ndarray:
        self.wait_to_read()
        if _transfer_observer is not None:
            _transfer_observer(self)
        return onp.asarray(self._data)

    def item(self):
        return self.asnumpy().item()

    def asscalar(self):
        return self.item()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size != 1:
            raise MXNetError(
                "The truth value of an ndarray with multiple elements is ambiguous."
            )
        return bool(self.item())

    def __len__(self) -> int:
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __array__(self, dtype=None):
        arr = self.asnumpy()
        return arr.astype(dtype) if dtype is not None else arr

    # jax interop: our arrays flow straight into jnp/pytree code
    def __jax_array__(self):
        return self._data

    def astype(self, dtype, copy: bool = True) -> "ndarray":
        dt = dtype_from_any(dtype)
        if not copy and self.dtype == dt:
            return self
        return apply_op(lambda x: x.astype(dt), (self,), name="astype")

    def copy(self) -> "ndarray":
        return apply_op(lambda x: x + 0, (self,), name="copy")

    def copyto(self, other: Union["ndarray", Context]) -> "ndarray":
        """Cross-device copy (reference src/ndarray/ndarray.cc CopyFromTo)."""
        from ..resilience import chaos

        chaos.site("device.put")
        if isinstance(other, Context):
            out = _wrap(jax.device_put(self._data, other.jax_device))
            return out
        other._set_data(
            jax.device_put(self._data.astype(other.dtype), other.ctx.jax_device)
        )
        return other

    def as_in_ctx(self, ctx: Context) -> "ndarray":
        if ctx == self.ctx:
            return self
        return self.copyto(ctx)

    as_in_context = as_in_ctx
    to_device = as_in_ctx

    def tolist(self):
        return self.asnumpy().tolist()

    # ------------------------------------------------------------------
    # autograd surface (reference python/mxnet/ndarray/ndarray.py attach_grad)
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None) -> None:
        if grad_req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {grad_req!r}")
        self._grad_req = grad_req
        if grad_req == "null":
            self._grad = None
        elif stype == "row_sparse":
            # sparse gradient storage (reference attach_grad stype arg →
            # kRowSparseStorage grad, ndarray.py:2747): starts empty; the
            # backward pass fills only the touched rows
            from .sparse import RowSparseNDArray

            self._grad = RowSparseNDArray(
                jnp.zeros((0,) + self.shape[1:], self.dtype),
                jnp.zeros((0,), jnp.int32), self.shape)
        else:
            self._grad = _wrap(jnp.zeros(self.shape, self.dtype))

    def detach(self) -> "ndarray":
        out = _wrap(self._data)
        return out

    @property
    def stype(self) -> str:
        """Storage type (reference ``NDArray.stype``): dense arrays are
        ``"default"``; RowSparseNDArray/CSRNDArray override."""
        return "default"

    def check_format(self, full_check: bool = True) -> None:
        """Validate storage-format integrity (reference
        ``NDArray.check_format`` / ``MXNDArraySyncCheckFormat``). Dense
        arrays are always well-formed; the sparse classes override with
        real index checks."""

    def backward(self, out_grad=None, retain_graph=False, train_mode=True) -> None:
        from ..ops import dispatch

        dispatch.backward(
            [self],
            [out_grad] if out_grad is not None else None,
            retain_graph=retain_graph,
            train_mode=train_mode,
        )

    # ------------------------------------------------------------------
    # mutation (rebind + version bump)
    # ------------------------------------------------------------------
    def _set_data(self, val) -> None:
        self._data = val
        self._version += 1

    def __setitem__(self, key, value) -> None:
        if is_recording() and self._grad_req != "null":
            raise MXNetError(
                "in-place assignment to an array that requires grad while recording"
            )
        val = _unwrap(value)
        if key is None or (isinstance(key, slice) and key == slice(None)):
            if not onp.isscalar(val) and getattr(val, "shape", ()) != self.shape:
                val = jnp.broadcast_to(jnp.asarray(val, self.dtype), self.shape)
            self._set_data(jnp.asarray(val, self.dtype) * jnp.ones(self.shape, self.dtype) if onp.isscalar(val) else jnp.asarray(val, self.dtype))
            return
        self._check_int_index(key)  # jnp scatter silently drops OOB writes
        key = _unwrap_index(key)
        self._set_data(self._data.at[key].set(jnp.asarray(val, self.dtype) if not onp.isscalar(val) else val))

    @staticmethod
    def _is_plain_int(k) -> bool:
        return isinstance(k, (int, onp.integer)) and not isinstance(
            k, (bool, onp.bool_))

    def _check_int_index(self, key) -> None:
        """numpy contract: out-of-range integer indexing raises IndexError
        (jnp clamps gathers / drops scatters, which would also make the
        legacy __getitem__ iteration protocol loop forever). bool is an
        int subclass but means mask/newaxis indexing — excluded; array
        keys are not checked (a bounds check would force a device sync)."""
        if not hasattr(self._data, "ndim"):
            return  # tuple-valued results (control-flow ops) index as-is

        def check(k, axis):
            if self._is_plain_int(k):
                if axis >= self.ndim:
                    raise IndexError(
                        f"too many indices for {self.ndim}-d array")
                n = self.shape[axis]
                if not -n <= k < n:
                    raise IndexError(f"index {k} is out of bounds for "
                                     f"axis {axis} with size {n}")

        if isinstance(key, tuple):
            entries = [k for k in key if k is not None]
            if any(getattr(k, "ndim", 0) > 0 for k in entries):
                return  # advanced indexing: axis mapping is nontrivial
            if Ellipsis in [k for k in entries if not hasattr(k, "shape")]:
                i = next(j for j, k in enumerate(entries) if k is Ellipsis)
                before, after = entries[:i], entries[i + 1:]
            else:
                before, after = entries, []
            for ax, k in enumerate(before):
                check(k, ax)
            for j, k in enumerate(after):
                check(k, self.ndim - len(after) + j)
        else:
            check(key, 0)

    def __getitem__(self, key) -> "ndarray":
        self._check_int_index(key)
        key = _unwrap_index(key)
        return apply_op(lambda x: x[key], (self,), name="getitem")

    def __iter__(self):
        if self.ndim == 0:
            raise TypeError("iteration over a 0-d array")
        return (self[i] for i in range(self.shape[0]))

    # ------------------------------------------------------------------
    # shape ops
    # ------------------------------------------------------------------
    def reshape(self, *shape) -> "ndarray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = tuple(-1 if s in (-1, 0) and s == -1 else s for s in shape)
        return apply_op(lambda x: x.reshape(shape), (self,), name="reshape")

    def transpose(self, *axes) -> "ndarray":
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        ax = axes if axes else None
        return apply_op(lambda x: jnp.transpose(x, ax), (self,), name="transpose")

    def flatten(self) -> "ndarray":
        return self.reshape(-1)

    def squeeze(self, axis=None) -> "ndarray":
        return apply_op(lambda x: jnp.squeeze(x, axis), (self,), name="squeeze")

    def expand_dims(self, axis) -> "ndarray":
        return apply_op(lambda x: jnp.expand_dims(x, axis), (self,), name="expand_dims")

    def broadcast_to(self, shape) -> "ndarray":
        return apply_op(lambda x: jnp.broadcast_to(x, tuple(shape)), (self,), name="broadcast_to")

    def swapaxes(self, a1, a2) -> "ndarray":
        return apply_op(lambda x: jnp.swapaxes(x, a1, a2), (self,), name="swapaxes")

    # ------------------------------------------------------------------
    # reductions / common methods
    # ------------------------------------------------------------------
    def sum(self, axis=None, dtype=None, keepdims=False) -> "ndarray":
        dt = dtype_from_any(dtype) if dtype is not None else None
        return apply_op(lambda x: jnp.sum(x, axis=axis, dtype=dt, keepdims=keepdims), (self,), name="sum")

    def mean(self, axis=None, dtype=None, keepdims=False) -> "ndarray":
        dt = dtype_from_any(dtype) if dtype is not None else None
        return apply_op(lambda x: jnp.mean(x, axis=axis, dtype=dt, keepdims=keepdims), (self,), name="mean")

    def max(self, axis=None, keepdims=False) -> "ndarray":
        return apply_op(lambda x: jnp.max(x, axis=axis, keepdims=keepdims), (self,), name="max")

    def min(self, axis=None, keepdims=False) -> "ndarray":
        return apply_op(lambda x: jnp.min(x, axis=axis, keepdims=keepdims), (self,), name="min")

    def prod(self, axis=None, dtype=None, keepdims=False) -> "ndarray":
        dt = dtype_from_any(dtype) if dtype is not None else None
        return apply_op(lambda x: jnp.prod(x, axis=axis, dtype=dt, keepdims=keepdims), (self,), name="prod")

    def all(self, axis=None, keepdims=False) -> "ndarray":
        return apply_op(lambda x: jnp.all(x, axis=axis, keepdims=keepdims), (self,), name="all")

    def any(self, axis=None, keepdims=False) -> "ndarray":
        return apply_op(lambda x: jnp.any(x, axis=axis, keepdims=keepdims), (self,), name="any")

    def argmax(self, axis=None) -> "ndarray":
        return apply_op(lambda x: jnp.argmax(x, axis=axis), (self,), name="argmax")

    def argmin(self, axis=None) -> "ndarray":
        return apply_op(lambda x: jnp.argmin(x, axis=axis), (self,), name="argmin")

    def clip(self, a_min=None, a_max=None) -> "ndarray":
        return apply_op(lambda x: jnp.clip(x, a_min, a_max), (self,), name="clip")

    def dot(self, other) -> "ndarray":
        return apply_op(lambda a, b: jnp.dot(a, b), (self, other), name="dot")

    def abs(self) -> "ndarray":
        return apply_op(jnp.abs, (self,), name="abs")

    def round(self) -> "ndarray":
        return apply_op(jnp.round, (self,), name="round")

    def cumsum(self, axis=None) -> "ndarray":
        return apply_op(lambda x: jnp.cumsum(x, axis=axis), (self,), name="cumsum")

    def take(self, indices, axis=None) -> "ndarray":
        return apply_op(
            lambda x, i: jnp.take(x, i.astype(jnp.int32) if hasattr(i, "astype") else i, axis=axis),
            (self, indices),
            name="take",
        )

    def item_size(self):
        return self.dtype.itemsize

    def __repr__(self) -> str:
        try:
            body = str(self.asnumpy())
        except Exception:
            body = f"<abstract {self.shape} {self.dtype}>"
        return f"{body}\n<ndarray {self.shape} @{self.ctx} {self.dtype}>"

    # pickle support (DataLoader workers, block export): device buffers
    # travel as host numpy and are re-uploaded on unpickle
    def __getstate__(self):
        return {"data": self.asnumpy(), "grad_req": self._grad_req}

    def __setstate__(self, state):
        self._data = jnp.asarray(state["data"])
        self._grad = None
        self._grad_req = "null"
        self._fresh_grad_node = None
        self._version = 0
        if state.get("grad_req", "null") != "null":
            self.attach_grad(state["grad_req"])

    def __reduce__(self):
        return (_rebuild_ndarray, (self.__getstate__(),))

    # ------------------------------------------------------------------
    # arithmetic operators
    # ------------------------------------------------------------------
    def _binop(self, other, fn, name, reverse=False):
        if isinstance(other, (list, tuple, onp.ndarray)):
            other = _wrap(jnp.asarray(other))
        args = (other, self) if reverse else (self, other)
        return apply_op(fn, args, name=name)

    def __add__(self, o):
        return self._binop(o, operator.add, "add")

    def __radd__(self, o):
        return self._binop(o, operator.add, "add", reverse=True)

    def __sub__(self, o):
        return self._binop(o, operator.sub, "sub")

    def __rsub__(self, o):
        return self._binop(o, operator.sub, "sub", reverse=True)

    def __mul__(self, o):
        return self._binop(o, operator.mul, "mul")

    def __rmul__(self, o):
        return self._binop(o, operator.mul, "mul", reverse=True)

    def __truediv__(self, o):
        return self._binop(o, operator.truediv, "div")

    def __rtruediv__(self, o):
        return self._binop(o, operator.truediv, "div", reverse=True)

    def __mod__(self, o):
        return self._binop(o, operator.mod, "mod")

    def __rmod__(self, o):
        return self._binop(o, operator.mod, "mod", reverse=True)

    def __floordiv__(self, o):
        return self._binop(o, operator.floordiv, "floordiv")

    def __pow__(self, o):
        return self._binop(o, operator.pow, "pow")

    def __rpow__(self, o):
        return self._binop(o, operator.pow, "pow", reverse=True)

    def __matmul__(self, o):
        return self._binop(o, operator.matmul, "matmul")

    def __neg__(self):
        return apply_op(operator.neg, (self,), name="neg")

    def __abs__(self):
        return self.abs()

    # in-place operators rebind (engine write-dependency analog)
    def __iadd__(self, o):
        self._set_data(self._data + _unwrap(o))
        return self

    def __isub__(self, o):
        self._set_data(self._data - _unwrap(o))
        return self

    def __imul__(self, o):
        self._set_data(self._data * _unwrap(o))
        return self

    def __itruediv__(self, o):
        self._set_data(self._data / _unwrap(o))
        return self

    # comparisons (non-differentiable)
    def _cmp(self, other, fn, name):
        return apply_op(fn, (self, _coerce(other)), name=name)

    def __eq__(self, o):
        return self._cmp(o, lambda a, b: a == b, "eq")

    def __ne__(self, o):
        return self._cmp(o, lambda a, b: a != b, "ne")

    def __lt__(self, o):
        return self._cmp(o, lambda a, b: a < b, "lt")

    def __le__(self, o):
        return self._cmp(o, lambda a, b: a <= b, "le")

    def __gt__(self, o):
        return self._cmp(o, lambda a, b: a > b, "gt")

    def __ge__(self, o):
        return self._cmp(o, lambda a, b: a >= b, "ge")

    __hash__ = object.__hash__


def _coerce(x):
    if isinstance(x, (list, tuple, onp.ndarray)):
        return _wrap(jnp.asarray(x))
    return x


def _unwrap_index(key):
    if isinstance(key, ndarray):
        return key._data
    if isinstance(key, tuple):
        return tuple(_unwrap_index(k) for k in key)
    return key


def _rebuild_ndarray(state):
    out = ndarray.__new__(ndarray)
    out.__setstate__(state)
    return out


NDArray = ndarray


# -- fluent methods (reference numpy/multiarray.py) -------------------------
# The reference ndarray keeps a small set of REAL fluent delegations
# (multiarray.py:1733 sort, :1749 argsort, std/var/repeat/tile/nonzero,
# reshape_view, slice_assign*) and deliberately raises AttributeError for
# the legacy nd fluent surface (exp/log/relu/...) — absence here matches
# that contract exactly.

def _fluent(op_name):
    def method(self, *args, **kwargs):
        from .. import numpy as _np

        return getattr(_np, op_name)(self, *args, **kwargs)

    method.__name__ = op_name
    method.__doc__ = (f"Convenience fluent method for mx.np.{op_name} "
                      f"with this array as the first argument.")
    return method


for _name in ("sort", "argsort", "std", "var", "repeat", "tile", "nonzero"):
    setattr(ndarray, _name, _fluent(_name))


def _as_np_ndarray(self):
    return self


def _reshape_view(self, *shape):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return self.reshape(shape)


def _slice_assign(self, rhs, begin, end, step=None):
    """Eager in-place region assign, returns self. Like ``__setitem__``
    (which it delegates to), this mutates and is therefore REJECTED on a
    grad-attached array inside ``autograd.record()`` — use
    ``npx.index_update`` for a functional, differentiable update."""
    step = step or [1] * len(begin)
    key = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))
    self[key] = rhs
    return self


ndarray.as_np_ndarray = _as_np_ndarray
ndarray.as_nd_ndarray = _as_np_ndarray  # unified array type on TPU
ndarray.reshape_view = _reshape_view
ndarray.slice_assign = _slice_assign
ndarray.slice_assign_scalar = _slice_assign


def array(obj, dtype=None, ctx=None, device=None) -> ndarray:
    return ndarray(obj, ctx=ctx or device, dtype=dtype)


# register as a pytree leaf container so jax.tree_util flattens through it
jax.tree_util.register_pytree_node(
    ndarray,
    lambda a: ((a._data,), None),
    lambda aux, children: _wrap(children[0]),
)
