"""Sparse storage types (reference ``include/mxnet/ndarray.h:63-65``
``kRowSparseStorage``/``kCSRStorage`` + ``python/mxnet/ndarray/sparse.py``).

TPU-native design (SURVEY.md §7 "hard parts"): XLA has no sparse tensor
type, so sparse storage is a *pair of dense jax arrays* (indices + values)
and every sparse op lowers to gather/scatter/segment-sum — which is how
embedding-gradient sparsity is actually exploited on TPU hardware (the MXU
wants dense tiles; the win is touching only ``nnz`` rows of HBM instead of
the full vocab). ``row_sparse`` is the gradient format for embeddings
(reference src/operator/tensor/indexing_op.cc EmbeddingOpBackward w/
kRowSparseStorage output); ``csr`` covers sample-major sparse inputs
(reference src/io libsvm iterator use case).
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from .ndarray import ndarray, _unwrap, _wrap

__all__ = [
    "RowSparseNDArray",
    "CSRNDArray",
    "row_sparse_array",
    "csr_matrix",
    "cast_storage",
    "retain",
    "dot",
    "add",
    "stype_of",
]


def stype_of(arr) -> str:
    return getattr(arr, "stype", "default")


class BaseSparseNDArray:
    """Common surface so sparse arrays duck-type where dense ndarray goes."""

    stype = "undefined"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return onp.dtype(self._values.dtype) if str(
            self._values.dtype) != "bfloat16" else self._values.dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def data(self):
        """The values array (reference sparse.py RowSparseNDArray.data)."""
        return _wrap(self._values)

    def asnumpy(self) -> onp.ndarray:
        self.wait_to_read()  # surfaces (and marks observed) deferred errors
        return onp.asarray(self.todense_val())

    def wait_to_read(self):
        try:
            self._values.block_until_ready()
        except AttributeError:
            pass  # tracer
        except Exception:
            # error observed here → clear from the engine's pending set so
            # waitall() does not rethrow it (same contract as dense
            # ndarray.wait_to_read)
            from .. import engine as _engine

            _engine.observed(self._values)
            raise

    def tostype(self, stype: str):
        if stype == self.stype:
            return self
        if stype == "default":
            return _wrap(self.todense_val())
        raise MXNetError(f"cast_storage {self.stype} -> {stype} not supported")

    def __repr__(self):
        return (f"<{type(self).__name__} {self._shape} nnz={self.nnz} "
                f"dtype={self._values.dtype}>")


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse array: a subset of rows present (reference ndarray.h:64
    kRowSparseStorage; python/mxnet/ndarray/sparse.py:570).

    ``indices``: int32 (nnz,) row ids (kept sorted+unique by construction
    through ``consolidate``); ``values``: (nnz,) + shape[1:].
    """

    stype = "row_sparse"

    def __init__(self, values, indices, shape):
        self._values = _unwrap(values)
        self._indices = jnp.asarray(_unwrap(indices), jnp.int32)
        self._shape = tuple(int(s) for s in shape)
        if self._values.ndim != len(self._shape):
            raise MXNetError(
                f"row_sparse values ndim {self._values.ndim} != dense ndim "
                f"{len(self._shape)} (values carry the full row shape)")

    @property
    def indices(self):
        return _wrap(self._indices)

    @property
    def nnz(self) -> int:
        return int(self._indices.shape[0])

    def todense_val(self):
        out = jnp.zeros(self._shape, self._values.dtype)
        if self.nnz == 0:
            return out
        return out.at[self._indices].add(self._values)

    def check_format(self, full_check: bool = True) -> None:
        """Reference ``check_format``: one index per value row; row ids
        in-range, sorted, and unique."""
        idx = onp.asarray(self._indices)
        if idx.shape[0] != self._values.shape[0]:
            raise MXNetError(
                f"row_sparse indices length {idx.shape[0]} != values rows "
                f"{self._values.shape[0]}")
        if idx.size == 0:
            return
        if idx.min() < 0 or idx.max() >= self._shape[0]:
            raise MXNetError(
                f"row_sparse indices out of range [0, {self._shape[0]})")
        if full_check and (onp.any(onp.diff(idx) <= 0)):
            raise MXNetError(
                "row_sparse indices must be sorted and unique "
                "(call consolidate())")

    def consolidate(self) -> "RowSparseNDArray":
        """Sort + dedupe row ids, summing duplicate rows (segment-sum —
        the TPU equivalent of the reference's dedup in sparse kvstore)."""
        if self.nnz == 0:
            return self
        uniq, inv = onp.unique(onp.asarray(self._indices), return_inverse=True)
        if uniq.shape[0] == self._indices.shape[0] and bool(
                onp.all(onp.asarray(self._indices) == uniq)):
            return self
        summed = jax.ops.segment_sum(self._values, jnp.asarray(inv),
                                     num_segments=int(uniq.shape[0]))
        return RowSparseNDArray(summed, jnp.asarray(uniq, jnp.int32),
                                self._shape)

    def retain(self, row_ids) -> "RowSparseNDArray":
        """Keep only the requested rows (reference sparse retain op)."""
        rs = self.consolidate()
        keep = jnp.asarray(_unwrap(row_ids), jnp.int32)
        mask = jnp.isin(rs._indices, keep)
        idx = onp.nonzero(onp.asarray(mask))[0]
        return RowSparseNDArray(rs._values[idx], rs._indices[idx], self._shape)

    def copy(self) -> "RowSparseNDArray":
        return RowSparseNDArray(self._values, self._indices, self._shape)

    def astype(self, dtype):
        return RowSparseNDArray(self._values.astype(dtype), self._indices,
                                self._shape)

    # -- arithmetic used by the autograd tape ------------------------------
    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            if other._shape != self._shape:
                raise MXNetError("row_sparse add: shape mismatch")
            return RowSparseNDArray(
                jnp.concatenate([self._values, other._values], axis=0),
                jnp.concatenate([self._indices, other._indices], axis=0),
                self._shape)
        # dense + sparse -> dense
        dense = _unwrap(other)
        return dense.at[self._indices].add(
            self._values.astype(dense.dtype)) if hasattr(
                dense, "at") else self.todense_val() + dense

    __radd__ = __add__

    def __mul__(self, scalar):
        return RowSparseNDArray(self._values * scalar, self._indices,
                                self._shape)

    __rmul__ = __mul__


class CSRNDArray(BaseSparseNDArray):
    """2-D compressed-sparse-row array (reference ndarray.h:65 kCSRStorage;
    python/mxnet/ndarray/sparse.py:340)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        self._values = _unwrap(data)
        self._indices = jnp.asarray(_unwrap(indices), jnp.int32)
        self._indptr = jnp.asarray(_unwrap(indptr), jnp.int32)
        self._shape = tuple(int(s) for s in shape)
        if len(self._shape) != 2:
            raise MXNetError("csr storage is 2-D only")

    @property
    def indices(self):
        return _wrap(self._indices)

    @property
    def indptr(self):
        return _wrap(self._indptr)

    @property
    def nnz(self) -> int:
        return int(self._values.shape[0])

    def check_format(self, full_check: bool = True) -> None:
        """Reference ``check_format``: indptr must be monotone from 0 to
        nnz with one entry per row boundary; column ids in range (and
        sorted within each row under ``full_check``)."""
        ptr = onp.asarray(self._indptr)
        idx = onp.asarray(self._indices)
        if ptr.shape[0] != self._shape[0] + 1:
            raise MXNetError("csr indptr length must be rows+1")
        if idx.shape[0] != self.nnz:
            raise MXNetError(
                f"csr indices length {idx.shape[0]} != nnz {self.nnz}")
        if ptr[0] != 0 or ptr[-1] != self.nnz or onp.any(onp.diff(ptr) < 0):
            raise MXNetError("csr indptr must rise monotonically 0 -> nnz")
        if idx.size and (idx.min() < 0 or idx.max() >= self._shape[1]):
            raise MXNetError(
                f"csr indices out of range [0, {self._shape[1]})")
        if full_check and idx.size > 1:
            # vectorized within-row sortedness: a decrease is legal only
            # at a row boundary (positions where some ptr value == i+1)
            d = onp.diff(idx)
            boundary = onp.zeros(idx.size - 1, bool)
            inner = ptr[(ptr > 0) & (ptr < idx.size)]
            boundary[inner - 1] = True
            if onp.any((d <= 0) & ~boundary):
                raise MXNetError(
                    "csr column ids must be sorted and unique within "
                    "each row")

    def _row_ids(self):
        """Expand indptr to one row id per nnz element."""
        counts = onp.diff(onp.asarray(self._indptr))
        return jnp.asarray(onp.repeat(onp.arange(self._shape[0]), counts),
                           jnp.int32)

    def todense_val(self):
        out = jnp.zeros(self._shape, self._values.dtype)
        if self.nnz == 0:
            return out
        return out.at[self._row_ids(), self._indices].add(self._values)

    def copy(self) -> "CSRNDArray":
        return CSRNDArray(self._values, self._indices, self._indptr,
                          self._shape)

    def astype(self, dtype):
        return CSRNDArray(self._values.astype(dtype), self._indices,
                          self._indptr, self._shape)


def row_sparse_array(arg, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    """Construct row_sparse from (values, indices) or densify-from-dense
    (reference sparse.py:1059 row_sparse_array)."""
    if isinstance(arg, RowSparseNDArray):
        return arg
    if isinstance(arg, (tuple, list)) and len(arg) == 2:
        values, indices = arg
        values = jnp.asarray(_unwrap(values),
                             jnp.dtype(dtype) if dtype else None)
        if shape is None:
            raise MXNetError("row_sparse_array((values, indices)) needs shape")
        return RowSparseNDArray(values, indices, shape).consolidate()
    dense = onp.asarray(arg.asnumpy() if isinstance(arg, ndarray) else arg,
                        dtype=dtype)
    rows = onp.nonzero(onp.any(dense != 0, axis=tuple(range(1, dense.ndim))))[0]
    return RowSparseNDArray(jnp.asarray(dense[rows]),
                            jnp.asarray(rows, jnp.int32), dense.shape)


def csr_matrix(arg, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    """Construct CSR from (data, indices, indptr) or from dense
    (reference sparse.py:910 csr_matrix)."""
    if isinstance(arg, CSRNDArray):
        return arg
    if isinstance(arg, (tuple, list)) and len(arg) == 3:
        data, indices, indptr = arg
        if shape is None:
            raise MXNetError("csr_matrix((data, indices, indptr)) needs shape")
        return CSRNDArray(jnp.asarray(_unwrap(data),
                                      jnp.dtype(dtype) if dtype else None),
                          indices, indptr, shape)
    dense = onp.asarray(arg.asnumpy() if isinstance(arg, ndarray) else arg,
                        dtype=dtype)
    if dense.ndim != 2:
        raise MXNetError("csr_matrix from dense needs a 2-D array")
    indptr = [0]
    cols, vals = [], []
    for row in dense:
        nz = onp.nonzero(row)[0]
        cols.extend(nz.tolist())
        vals.extend(row[nz].tolist())
        indptr.append(len(cols))
    return CSRNDArray(jnp.asarray(onp.asarray(vals, dense.dtype)),
                      onp.asarray(cols, onp.int32),
                      onp.asarray(indptr, onp.int32), dense.shape)


def cast_storage(arr, stype: str):
    """reference src/operator/tensor/cast_storage.cc."""
    current = stype_of(arr)
    if current == stype:
        return arr
    if stype == "default":
        return arr.tostype("default")
    if current == "default":
        if stype == "row_sparse":
            return row_sparse_array(arr)
        if stype == "csr":
            return csr_matrix(arr)
    elif current == "row_sparse" and stype == "csr":
        return csr_matrix(arr.tostype("default"))
    elif current == "csr" and stype == "row_sparse":
        return row_sparse_array(arr.tostype("default"))
    raise MXNetError(f"cast_storage {current} -> {stype} not supported")


def retain(arr: RowSparseNDArray, row_ids) -> RowSparseNDArray:
    if stype_of(arr) != "row_sparse":
        raise MXNetError("retain expects a row_sparse array")
    return arr.retain(row_ids)


def dot(lhs, rhs, transpose_a: bool = False, transpose_b: bool = False):
    """Sparse-aware dot (reference src/operator/tensor/dot.cc sparse
    kernels). csr x dense and row_sparse^T x dense lower to
    gather/segment-sum — dense MXU work on just the nnz rows."""
    ls, rs = stype_of(lhs), stype_of(rhs)
    if ls == "csr" and rs == "default":
        rhs_v = _unwrap(rhs)
        if transpose_b:
            raise MXNetError("sparse.dot: transpose_b unsupported for csr lhs")
        expect = lhs.shape[0] if transpose_a else lhs.shape[1]
        if rhs_v.shape[0] != expect:
            raise MXNetError(
                f"sparse.dot: contraction mismatch csr{lhs.shape}"
                f"{'^T' if transpose_a else ''} x dense{rhs_v.shape}")
        if transpose_a:
            # (csr^T @ dense): scatter-add rows of rhs into column slots
            out = jnp.zeros((lhs.shape[1], rhs_v.shape[1]), rhs_v.dtype)
            contrib = lhs._values[:, None] * rhs_v[lhs._row_ids()]
            return _wrap(out.at[lhs._indices].add(contrib))
        # row-major gather: out[i] = sum_k csr[i,k] * rhs[k]
        gathered = rhs_v[lhs._indices] * lhs._values[:, None]
        out = jax.ops.segment_sum(gathered, lhs._row_ids(),
                                  num_segments=lhs.shape[0])
        return _wrap(out)
    if ls == "row_sparse" and rs == "default" and transpose_a:
        # rs^T @ dense — the embedding-gradient pattern
        lhs = lhs.consolidate()
        rhs_v = _unwrap(rhs)
        # values (nnz, R) x gathered rhs rows (nnz, C) -> (R, C)
        return _wrap(jnp.einsum("nr,nc->rc", lhs._values.astype(rhs_v.dtype),
                                rhs_v[lhs._indices]))
    if ls == "default" and rs == "default":
        import jax.numpy as _jnp

        return _wrap(_jnp.dot(_unwrap(lhs).T if transpose_a else _unwrap(lhs),
                              _unwrap(rhs).T if transpose_b else _unwrap(rhs)))
    raise MXNetError(f"sparse dot: unsupported stypes ({ls}, {rs})")


def add(lhs, rhs):
    """Elementwise add with sparse-storage awareness."""
    if stype_of(lhs) == "row_sparse" and stype_of(rhs) == "row_sparse":
        return (lhs + rhs).consolidate()
    if stype_of(lhs) == "row_sparse":
        return _wrap(lhs + _unwrap(rhs))
    if stype_of(rhs) == "row_sparse":
        return _wrap(rhs + _unwrap(lhs))
    return _wrap(_unwrap(lhs) + _unwrap(rhs))
