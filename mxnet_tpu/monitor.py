"""``mx.monitor`` — training observability taps.

Parity targets:
- ``python/mxnet/monitor.py`` ``Monitor``: periodically collect a statistic
  over intermediate outputs (and optionally parameters) whose names match a
  regex; ``install``/``tic``/``toc``/``toc_print`` lifecycle.
- ``src/common/tensor_inspector.h`` ``TensorInspector``: interactive value
  dumps + value checks (negative/nan/inf) on a single tensor.

TPU-first notes: the reference installs a C++ callback on every executor op
via ``MXExecutorSetMonitorCallback``; ops here are fused into one XLA
program, so per-op taps are re-created at the two places user-visible
values still exist — Block boundaries (forward hooks) and symbol-executor
heads (``get_internals`` re-evaluation). Statistics are computed lazily on
device and only fetched at ``toc`` time to keep taps off the hot path.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as onp

from .base import MXNetError
from .ndarray.ndarray import ndarray

__all__ = ["Monitor", "TensorInspector"]


class Monitor:
    """Collect statistics of intermediate outputs every ``interval`` batches.

    Parameters follow the reference (monitor.py): ``stat_func`` maps an
    ndarray to a scalar/small ndarray statistic (default: mean(|x|)),
    ``pattern`` filters tap names, ``monitor_all`` additionally taps block
    parameters (reference taps op *inputs* with the same flag).
    """

    def __init__(self, interval: int = 1,
                 stat_func: Optional[Callable[[ndarray], Any]] = None,
                 pattern: str = ".*", sort: bool = False,
                 monitor_all: bool = False):
        if stat_func is None:
            def stat_func(x):
                from . import numpy as np

                return np.mean(np.abs(x))
        self.stat_func = stat_func
        self.interval = int(interval)
        self.pattern = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all
        self.activated = False
        self.step = 0
        self.queue: List[Tuple[int, str, Any]] = []
        self._handles: List[Any] = []
        self._exes: List[Any] = []
        self._blocks: List[Any] = []

    # -- installation -------------------------------------------------------
    def install(self, target, name: Optional[str] = None) -> None:
        """Attach to a :class:`~mxnet_tpu.symbol.Executor` or a gluon
        ``Block`` (recursively taps every child block's output)."""
        from .gluon.block import Block
        from .symbol import Executor

        if isinstance(target, Executor):
            self._exes.append((name or "exe%d" % len(self._exes), target))
        elif isinstance(target, Block):
            prefix = name or type(target).__name__.lower()
            if getattr(target, "_active", False):
                import warnings

                warnings.warn(
                    "Monitor installed on a hybridized block: child forward "
                    "hooks do not run inside the cached XLA graph, so only "
                    "the top-level output is tapped. Call hybridize(False) "
                    "while monitoring for per-layer taps.", stacklevel=2)
            # params are collected from install roots only (recursively via
            # collect_params) — child blocks get hooks, not param taps
            self._blocks.append((prefix, target))
            self._install_block(target, prefix)
        else:
            raise MXNetError(
                f"Monitor.install expects an Executor or Block, got "
                f"{type(target).__name__}")

    def _install_block(self, block, prefix: str) -> None:

        def make_hook(tap_name):
            def hook(blk, args, out):
                if not self.activated:
                    return
                import jax

                leaves = [v for v in jax.tree_util.tree_leaves(
                    out, is_leaf=lambda v: isinstance(v, ndarray))
                    if isinstance(v, ndarray)]
                for i, leaf in enumerate(leaves):
                    nm = tap_name if len(leaves) == 1 else f"{tap_name}_out{i}"
                    if self.pattern.match(nm):
                        self.queue.append((self.step, nm, self.stat_func(leaf)))
            return hook

        self._handles.append(
            block.register_forward_hook(make_hook(prefix + "_output")))
        for child_name, child in getattr(block, "_children", {}).items():
            self._install_block(child, f"{prefix}.{child_name}")

    # -- lifecycle ----------------------------------------------------------
    def tic(self) -> None:
        """Start collecting for this batch (if the interval says so)."""
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []

    def toc(self) -> List[Tuple[int, str, str]]:
        """Stop collecting; return [(step, name, formatted stat), ...]."""
        if not self.activated:
            self.step += 1
            return []
        # executor taps: re-evaluate internals at toc time
        for exe_name, exe in self._exes:
            sym = exe._symbol.get_internals()
            names = sym.list_outputs()
            outs = sym._evaluate(dict(exe.arg_dict))
            for nm, out in zip(names, outs):
                if self.pattern.match(nm):
                    self.queue.append((self.step, nm, self.stat_func(out)))
        if self.monitor_all:
            for prefix, block in self._blocks:
                for pname, p in block.collect_params().items():
                    full = f"{prefix}.{pname}"
                    if p._data is not None and self.pattern.match(full):
                        self.queue.append(
                            (self.step, full, self.stat_func(p.data())))
        self.activated = False
        res = []
        queue = sorted(self.queue, key=lambda q: q[1]) if self.sort \
            else self.queue
        for step, name, stat in queue:
            # exactly one conversion: asnumpy() is already a host array
            # (no onp.asarray re-wrap), and host-side stats pass through
            # onp.asarray without a copy
            if isinstance(stat, ndarray):
                val = stat.asnumpy()
            else:
                val = onp.asarray(stat)
            res.append((step, name, onp.array2string(val, precision=5)))
        self.step += 1
        self.queue = []
        return res

    def toc_print(self) -> None:
        for step, name, stat in self.toc():
            print(f"Batch: {step:7d} {name:30s} {stat}")

    def uninstall(self) -> None:
        for h in self._handles:
            h.detach()
        self._handles = []
        self._exes = []
        self._blocks = []


class TensorInspector:
    """Value inspection on one tensor (reference tensor_inspector.h:
    ``print_string``, ``check_value`` with built-in negative/nan/inf
    checkers, ``dump_to_file``)."""

    NEGATIVE_CHECKER = staticmethod(lambda v: v < 0)
    POSITIVE_CHECKER = staticmethod(lambda v: v > 0)
    ZERO_CHECKER = staticmethod(lambda v: v == 0)
    NAN_CHECKER = staticmethod(lambda v: onp.isnan(v))
    INF_CHECKER = staticmethod(lambda v: onp.isinf(v))
    FINITE_CHECKER = staticmethod(lambda v: ~onp.isfinite(v))

    def __init__(self, data):
        if isinstance(data, ndarray):
            self._np = data.asnumpy()
        else:
            self._np = onp.asarray(data)

    def print_string(self) -> str:
        s = onp.array2string(self._np, threshold=64, precision=6)
        return f"Tensor{list(self._np.shape)} {self._np.dtype}:\n{s}"

    def check_value(self, checker, print_result: bool = True):
        """Return coordinates where ``checker`` flags values (reference
        returns the coordinate list and optionally prints it)."""
        mask = checker(self._np)
        coords = [tuple(int(c) for c in idx)
                  for idx in onp.argwhere(mask)]
        if print_result and coords:
            print(f"TensorInspector: {len(coords)} flagged values; "
                  f"first at {coords[0]}")
        return coords

    def dump_to_file(self, tag: str, step: int = 0) -> str:
        fname = f"{tag}_{step}.npy"
        onp.save(fname, self._np)
        return fname
