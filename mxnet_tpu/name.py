"""``mx.name`` — symbol naming scopes (reference
``python/mxnet/name.py``: ``NameManager`` :27, ``Prefix`` :74).

``with mx.name.Prefix("layer1_"):`` prefixes every auto-generated symbol
name created in the scope; a custom ``NameManager`` subclass can rename
arbitrarily. Thread-local, nestable, innermost wins — the contract the
reference implements with a global stack + __enter__/__exit__.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]


class _Stack(threading.local):
    def __init__(self):
        self.managers = []


_stack = _Stack()


class NameManager:
    """Assigns names to ops created while the scope is active."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        """Return ``name`` if given, else generate from ``hint``
        (reference name.py:44)."""
        if name:
            return name
        self._counter.setdefault(hint, 0)
        generated = f"{hint}{self._counter[hint]}"
        self._counter[hint] += 1
        return generated

    def __enter__(self):
        _stack.managers.append(self)
        return self

    def __exit__(self, *exc):
        _stack.managers.pop()
        return False


class Prefix(NameManager):
    """Prefix every auto-generated name (reference name.py:74)."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current() -> NameManager | None:
    return _stack.managers[-1] if _stack.managers else None
